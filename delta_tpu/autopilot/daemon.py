"""Autopilot daemon — the closed observe→decide→act→audit loop.

One :func:`run_once` pass over a table is the whole loop:

1. **observe** — run the doctor and the advisor (both already feed the
   journal/gauges);
2. **decide** — `planner.plan` merges their remedies through the shared
   action catalog, then the persistent action ledger filters cooldowns and
   contention backoff;
3. **act** — with dry-run OFF, a quiet window, and the one-table-at-a-time
   lock held, `executor.execute` runs each action under the cost caps;
4. **audit** — a fresh doctor report brackets every executed action and
   the predicted-vs-realized delta lands in the action ledger (journal
   kind ``autopilot``), which the NEXT `advise()` cites instead of
   re-recommending the executed action — the same closed-loop idiom as the
   router calibrator (`obs/calibration`).

The :class:`Autopilot` daemon (thread ``delta-autopilot``) just ticks
:func:`run_once` over registered tables every
``delta.tpu.autopilot.intervalMs``. Strictly opt-in
(``delta.tpu.autopilot.enabled``), and dry-run by default
(``delta.tpu.autopilot.dryRun``) — until an operator flips both, nothing
executes, and the journaled plans show exactly what WOULD have run.

Crash semantics match the rest of the engine: every action's ``started``
ledger entry is flushed to disk BEFORE execution, so a process death
mid-maintenance leaves the attempt visible and the cooldown armed — a
crash-looping autopilot cannot re-execute the same action on every
restart (torture-tested via ``TortureHarness(autopilot=True)``).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from delta_tpu.autopilot import executor, planner
from delta_tpu.obs import journal as journal_mod
from delta_tpu.obs.actions import MaintenanceAction
from delta_tpu.obs.actions import spec as actions_spec
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["Autopilot", "RunReport", "run_once", "status", "enabled",
           "dry_run", "last_runs", "reset"]

#: one-table-at-a-time: ONE maintenance action executes per process at any
#: moment, whichever thread (daemon or explicit run_once) got here first
_EXEC_LOCK = threading.Lock()

_STATE_LOCK = threading.Lock()
_LAST_RUNS: Dict[str, Dict[str, Any]] = {}  # path -> last RunReport dict
_DAEMON: Optional["Autopilot"] = None


def enabled() -> bool:
    return conf.get_bool("delta.tpu.autopilot.enabled", False)


def dry_run() -> bool:
    return conf.get_bool("delta.tpu.autopilot.dryRun", True)


@dataclass
class RunReport:
    """What one autopilot pass over one table observed and did."""

    path: str
    started_at_ms: int
    status: str = "ok"             # ok | journal disabled | deferred | busy
    dry_run: bool = True
    quiet: Dict[str, Any] = field(default_factory=dict)
    planned: List[Dict[str, Any]] = field(default_factory=list)
    planned_keys: List[str] = field(default_factory=list)
    cooled: List[str] = field(default_factory=list)   # keys inside cooldown
    #: rewrite-class actions the ``requireShadow`` guardrail held back,
    #: with the verdict + shadow evidence cited (`planner.shadow_gate`)
    shadow_filtered: List[Dict[str, Any]] = field(default_factory=list)
    backoff_until_ms: Optional[int] = None
    outcomes: List[Dict[str, Any]] = field(default_factory=list)
    duration_ms: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "startedAt": self.started_at_ms,
            "status": self.status,
            "dryRun": self.dry_run,
            "quiet": dict(self.quiet),
            "planned": list(self.planned),
            "plannedKeys": list(self.planned_keys),
            "cooldownFiltered": list(self.cooled),
            "shadowFiltered": list(self.shadow_filtered),
            "backoffUntil": self.backoff_until_ms,
            "outcomes": list(self.outcomes),
            "durationMs": round(self.duration_ms, 3),
        }


def _resolve_log(table):
    from delta_tpu.log.deltalog import DeltaLog

    if isinstance(table, str):
        return DeltaLog.for_table(table)
    return getattr(table, "delta_log", table)


def _finish(report: RunReport, t0: float) -> RunReport:
    report.duration_ms = (time.monotonic() - t0) * 1000.0
    with _STATE_LOCK:
        _LAST_RUNS[report.path] = report.to_dict()
    return report


def run_once(table, force: bool = False) -> RunReport:
    """One full autopilot pass over ``table`` (DeltaTable, DeltaLog, or
    path). ``force=True`` skips the quiet-window check (operator-invoked
    "run it NOW"); every other guardrail still applies. Safe to call with
    the daemon running — execution is serialized process-wide."""
    t0 = time.monotonic()
    delta_log = _resolve_log(table)
    log_path = delta_log.log_path
    now = delta_log.clock()
    report = RunReport(path=delta_log.data_path, started_at_ms=now,
                       dry_run=dry_run())
    with telemetry.record_operation("delta.utility.autopilot",
                                    path=delta_log.data_path):
        telemetry.bump_counter("autopilot.runs")
        telemetry.set_gauge("autopilot.lastRunTimestamp", now,
                            path=delta_log.data_path)
        if not journal_mod.enabled(log_path):
            # no journal = no durable ledger = no cooldowns: refusing to
            # act is the only safe posture
            report.status = "journal disabled"
            return _finish(report, t0)

        # -- observe ----------------------------------------------------
        from delta_tpu.obs.advisor import advise
        from delta_tpu.obs.doctor import doctor

        doc = doctor(delta_log)
        adv = advise(delta_log)

        # -- decide -----------------------------------------------------
        # one journal read per pass: advise() just flushed, so a single
        # parse serves the ledger, the backoff scan, and the quiet window.
        # Ledger/window math runs on WALL time — journal entries stamp
        # ts from time.time(), and delta_log.clock() is injectable (tests
        # pin it), so mixing the domains would freeze every cooldown
        entries = journal_mod.read_entries(log_path)
        ledger = [e for e in entries if e.get("kind") == "autopilot"]
        commits = [e for e in entries if e.get("kind") == "commit"]
        wall_now = int(time.time() * 1000)
        blocked = planner.cooldown_blocked(ledger, wall_now,
                                           log_path=log_path)
        backoff = planner.contention_backoff_until(ledger, wall_now,
                                                   log_path=log_path)
        actions = planner.plan(doc, adv)
        # requireShadow guardrail BEFORE the cooldown filter and the
        # dry-run return: a dry-run plan must show the suppression too —
        # that's the whole point of rehearsing
        actions, shadow_deferred = planner.shadow_gate(
            actions, log_path,
            entries=[e for e in entries if e.get("kind") == "shadow"])
        if shadow_deferred:
            report.shadow_filtered = shadow_deferred
            telemetry.bump_counter("autopilot.actions.deferred",
                                   len(shadow_deferred))
            for d in shadow_deferred:
                journal_mod.record_autopilot(
                    log_path, "deferred",
                    {"kind": d["kind"], "target": d["target"],
                     "shadow": d.get("shadow")},
                    durable=False,
                    reason=f"requireShadow: {d['reason']}")
        runnable: List[MaintenanceAction] = []
        for a in actions:
            if a.key in blocked:
                report.cooled.append(a.key)
            else:
                runnable.append(a)
        max_actions = conf.get_int("delta.tpu.autopilot.maxActionsPerRun", 4)
        runnable = runnable[:max_actions]
        if runnable:
            telemetry.bump_counter("autopilot.actions.planned",
                                   len(runnable))
        planned_keys = sorted(a.key for a in runnable)
        with _STATE_LOCK:
            prev_planned = (_LAST_RUNS.get(delta_log.data_path) or {}).get(
                "plannedKeys")
        if planned_keys != prev_planned:
            # journal the plan only when it CHANGED — a dry-run daemon
            # ticking over stable debt must not flood the journal with
            # identical entries every interval. Buffered write: "planned"
            # never arms a cooldown, so it needs no durable sync write.
            for a in runnable:
                journal_mod.record_autopilot(log_path, "planned",
                                             a.to_dict(), durable=False,
                                             dryRun=report.dry_run)
        report.planned = [a.to_dict() for a in runnable]
        report.planned_keys = planned_keys
        if not runnable:
            return _finish(report, t0)

        # -- guardrails before acting ------------------------------------
        if report.dry_run:
            # the journaled "planned" entries ARE the dry run's output
            report.status = "dry-run"
            return _finish(report, t0)
        if backoff is not None:
            report.status = "deferred"
            report.backoff_until_ms = backoff
            telemetry.bump_counter("autopilot.actions.deferred",
                                   len(runnable))
            for a in runnable:
                journal_mod.record_autopilot(
                    log_path, "deferred", a.to_dict(), durable=False,
                    reason=f"contention backoff until {backoff}")
            return _finish(report, t0)
        report.quiet = planner.quiet_window(log_path, wall_now,
                                            commits=commits)
        if not force and not report.quiet["quiet"]:
            report.status = "deferred"
            telemetry.bump_counter("autopilot.actions.deferred",
                                   len(runnable))
            for a in runnable:
                journal_mod.record_autopilot(
                    log_path, "deferred", a.to_dict(), durable=False,
                    reason="window not quiet",
                    window=report.quiet)
            return _finish(report, t0)
        if not _EXEC_LOCK.acquire(blocking=False):
            # another table's maintenance is mid-flight in this process
            report.status = "busy"
            telemetry.bump_counter("autopilot.actions.deferred",
                                   len(runnable))
            for a in runnable:
                journal_mod.record_autopilot(
                    log_path, "deferred", a.to_dict(), durable=False,
                    reason="maintenance executor busy (one table at a time)")
            return _finish(report, t0)

        # -- act + audit -------------------------------------------------
        try:
            _execute_plan(delta_log, runnable, doc, report, t0)
        finally:
            _EXEC_LOCK.release()
        return _finish(report, t0)


def _execute_plan(delta_log, runnable: List[MaintenanceAction],
                  doc, report: RunReport, t0: float) -> None:
    """Run the plan under the wall-clock budget, journaling each action's
    lifecycle durably and auditing predicted-vs-realized per action."""
    from delta_tpu.obs.doctor import doctor

    log_path = delta_log.log_path
    budget_ms = conf.get_int("delta.tpu.autopilot.budgetMs", 300_000)
    # maxBytesPerRun is a PER-RUN pool: each rewrite action draws from it
    # and the remainder caps the next one, so a run can never rewrite more
    # than the cap no matter how many actions the plan holds
    bytes_left = conf.get_int("delta.tpu.autopilot.maxBytesPerRun", 2 << 30)
    attempts_cap = conf.get_int("delta.tpu.autopilot.maxCommitAttempts", 3)
    # re-check cooldowns now that the exec lock is held: a concurrent
    # run_once (daemon tick + manual call) may have attempted an action
    # between our plan and our turn at the lock (wall time: ledger ts
    # stamps come from time.time())
    blocked_now = planner.cooldown_blocked(
        planner.ledger_entries(log_path), int(time.time() * 1000),
        log_path=log_path)
    before = doc
    for a in runnable:
        if a.key in blocked_now:
            report.cooled.append(a.key)
            report.outcomes.append({"action": a.key, "status": "skipped",
                                    "reason": "cooldown"})
            continue
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        if elapsed_ms > budget_ms:
            telemetry.bump_counter("autopilot.actions.skipped")
            journal_mod.record_autopilot(
                log_path, "skipped", a.to_dict(), durable=False,
                reason=f"run budget {budget_ms}ms exhausted "
                       f"({elapsed_ms:.0f}ms elapsed)")
            report.outcomes.append({"action": a.key, "status": "skipped",
                                    "reason": "runBudget"})
            continue
        # durable BEFORE acting: a crash mid-action must leave the attempt
        # on disk so the restarted process's cooldown check sees it. BOTH
        # the ledger entry and the sweep-proof sidecar must land — a
        # degraded journal directory (disk full, perms) cannot arm the
        # cooldown, and executing without one invites a crash loop
        journaled = journal_mod.record_autopilot(log_path, "started",
                                                 a.to_dict(), durable=True)
        mirrored = journal_mod.record_attempt(log_path, a.key, "started",
                                          int(time.time() * 1000))
        if not (journaled and mirrored):
            telemetry.bump_counter("autopilot.actions.skipped")
            report.outcomes.append({"action": a.key, "status": "skipped",
                                    "reason": "ledgerUnwritable"})
            continue
        try:
            result = executor.execute(delta_log, a,
                                      max_bytes=max(bytes_left, 0),
                                      attempts_cap=attempts_cap)
        except BaseException:
            # process-death class (SimulatedCrash in the harness): journal
            # the interruption best-effort and let it pierce — the started
            # entry above already armed the cooldown either way
            journal_mod.record_autopilot(log_path, "interrupted",
                                         a.to_dict())
            journal_mod.record_attempt(log_path, a.key, "interrupted",
                                   int(time.time() * 1000))
            raise
        if result.status == "executed":
            bytes_left -= int(result.metrics.get("numRemovedBytes") or 0)
        after = None
        if result.status == "executed" and (
                executor.audit_metrics(a.kind) is not None
                or actions_spec(a.kind).mutates_table):
            # re-measure after ANY executed mutating action — a ZORDER has
            # no audited doctor dimension of its own but still rewrites
            # files, and the NEXT action's audit must not credit that
            try:
                after = doctor(delta_log)
            except Exception:  # noqa: BLE001 — audit is best-effort
                after = None
        audit = executor.build_audit(a, before, after)
        journal_mod.record_autopilot(
            log_path, result.status, a.to_dict(),
            result=result.to_dict(), audit=audit)
        journal_mod.record_attempt(log_path, a.key, result.status,
                                   int(time.time() * 1000))
        report.outcomes.append({"action": a.key, "status": result.status,
                                "result": result.to_dict(),
                                "audit": audit})
        if result.status == "abortedContention":
            # one lost maintenance commit backs the WHOLE table off — the
            # remaining actions must not keep racing the same foreground
            # writers inside this very run; they defer to a later pass
            rest = runnable[runnable.index(a) + 1:]
            if rest:
                telemetry.bump_counter("autopilot.actions.deferred",
                                       len(rest))
            for b in rest:
                journal_mod.record_autopilot(
                    log_path, "deferred", b.to_dict(), durable=False,
                    reason="contention backoff (earlier action in this "
                           "run lost to a foreground writer)")
                report.outcomes.append({"action": b.key,
                                        "status": "deferred",
                                        "reason": "contentionBackoff"})
            break
        if after is not None:
            before = after  # the next action audits against fresh state


# ---------------------------------------------------------------------------
# Daemon
# ---------------------------------------------------------------------------


class Autopilot:
    """Per-process maintenance daemon: ticks :func:`run_once` over the
    registered tables every ``delta.tpu.autopilot.intervalMs`` on a
    ``delta-autopilot`` thread. Opt-in twice over — construction requires
    ``delta.tpu.autopilot.enabled=true``, and execution additionally
    requires ``delta.tpu.autopilot.dryRun=false``."""

    def __init__(self, tables: Optional[List[str]] = None):
        if not enabled():
            from delta_tpu.utils import errors

            raise errors.DeltaIllegalStateError(
                "the autopilot is opt-in: set delta.tpu.autopilot.enabled"
                "=true before starting it")
        self._tables: List[str] = list(tables or [])
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, path: str) -> None:
        with self._lock:
            if path not in self._tables:
                self._tables.append(path)

    def unregister(self, path: str) -> None:
        with self._lock:
            if path in self._tables:
                self._tables.remove(path)

    @property
    def tables(self) -> List[str]:
        with self._lock:
            return list(self._tables)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Autopilot":
        global _DAEMON
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="delta-autopilot")
        self._thread.start()
        with _STATE_LOCK:
            _DAEMON = self
        return self

    def stop(self, timeout: float = 5.0) -> None:
        global _DAEMON
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with _STATE_LOCK:
            if _DAEMON is self:
                _DAEMON = None

    def tick(self) -> None:
        """Wake the daemon for an immediate pass (tests, operators)."""
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            for path in self.tables:
                if self._stop.is_set():
                    break
                try:
                    run_once(path)
                except Exception:  # noqa: BLE001 — one table's failure must
                    # not starve the others; the ledger has the detail
                    telemetry.logger.warning(
                        "autopilot pass failed for %s", path, exc_info=True)
                # non-Exception BaseExceptions propagate and kill the
                # daemon thread — a simulated process death must not leave
                # a "dead" process's scheduler running (same narrowing as
                # log/checkpointer)
            interval = conf.get_int("delta.tpu.autopilot.intervalMs", 60_000)
            self._wake.wait(timeout=interval / 1000.0)
            self._wake.clear()


# ---------------------------------------------------------------------------
# Introspection (the /autopilot HTTP route serves this)
# ---------------------------------------------------------------------------


def last_runs() -> Dict[str, Dict[str, Any]]:
    with _STATE_LOCK:
        return {k: dict(v) for k, v in _LAST_RUNS.items()}


def status() -> Dict[str, Any]:
    """Process-wide autopilot status: conf posture, daemon state, and the
    last run report per table."""
    with _STATE_LOCK:
        daemon = _DAEMON
    return {
        "enabled": enabled(),
        "dryRun": dry_run(),
        "daemonRunning": daemon.running if daemon is not None else False,
        "tables": daemon.tables if daemon is not None else [],
        "intervalMs": conf.get_int("delta.tpu.autopilot.intervalMs", 60_000),
        "guardrails": {
            "maxBytesPerRun": conf.get_int("delta.tpu.autopilot.maxBytesPerRun", 2 << 30),
            "budgetMs": conf.get_int("delta.tpu.autopilot.budgetMs", 300_000),
            "maxActionsPerRun": conf.get_int("delta.tpu.autopilot.maxActionsPerRun", 4),
            "cooldownMs": conf.get_int("delta.tpu.autopilot.cooldownMs", 6 * 3_600_000),
            "contentionBackoffMs": conf.get_int("delta.tpu.autopilot.contentionBackoffMs", 300_000),
            "quietWindowMs": conf.get_int("delta.tpu.autopilot.quietWindowMs", 60_000),
            "quietMaxCommits": conf.get_int("delta.tpu.autopilot.quietMaxCommits", 0),
            "maxCommitAttempts": conf.get_int("delta.tpu.autopilot.maxCommitAttempts", 3),
        },
        "lastRuns": last_runs(),
    }


def reset() -> None:
    """Drop per-process autopilot state (tests / bench isolation). The
    on-disk action ledger is untouched — it lives in the journal."""
    global _DAEMON
    with _STATE_LOCK:
        daemon = _DAEMON
    if daemon is not None:
        daemon.stop(timeout=1.0)
    with _STATE_LOCK:
        _LAST_RUNS.clear()
        _DAEMON = None
