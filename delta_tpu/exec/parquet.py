"""Parquet read/write executor (host data plane, Arrow C++ underneath).

The role Spark's `ParquetFileFormat` + `FileFormatWriter` play in the
reference (`files/TransactionalWrite.scala:182-192`, `DeltaFileFormat.scala`)
— encode/decode Parquet, collect per-file column stats — lands on Arrow's
native Parquet module here. Stats collection follows the protocol's
per-column ``minValues``/``maxValues``/``nullCount`` + ``numRecords`` schema
(`PROTOCOL.md:441-480`), truncated to the first
``dataSkippingNumIndexedCols`` leaf columns (`DeltaConfig.scala:383`).
"""
from __future__ import annotations

import datetime as _dt
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

__all__ = ["write_parquet_file", "read_parquet_files", "collect_stats", "stats_json"]


def _stat_value(scalar: pa.Scalar, round_up: bool = False) -> Any:
    v = scalar.as_py()
    if isinstance(v, _dt.datetime):
        if round_up and v.microsecond % 1000:
            # maxValues truncated to ms must round UP or data skipping would
            # prune files containing sub-millisecond maxima
            v = v + _dt.timedelta(microseconds=1000 - v.microsecond % 1000)
        return v.strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
    if isinstance(v, _dt.date):
        return v.isoformat()
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return None
    if isinstance(v, bytes):
        return None  # binary stats not representable in JSON stats
    return v


def collect_stats(table: pa.Table, num_indexed_cols: int = 32) -> Dict[str, Any]:
    """Per-file stats over the first ``num_indexed_cols`` leaf columns."""
    mins: Dict[str, Any] = {}
    maxs: Dict[str, Any] = {}
    nulls: Dict[str, Any] = {}
    for name in table.column_names[: num_indexed_cols if num_indexed_cols >= 0 else None]:
        col = table.column(name)
        nulls[name] = col.null_count
        t = col.type
        skippable = (
            pa.types.is_integer(t)
            or pa.types.is_floating(t)
            or pa.types.is_string(t)
            or pa.types.is_date(t)
            or pa.types.is_timestamp(t)
            or pa.types.is_boolean(t)
            or pa.types.is_decimal(t)
        )
        if not skippable or col.null_count == len(col):
            continue
        try:
            mn = _stat_value(pc.min(col))
            mx = _stat_value(pc.max(col), round_up=True)
        except pa.ArrowNotImplementedError:
            continue
        if mn is not None:
            mins[name] = mn
        if mx is not None:
            maxs[name] = mx
    return {
        "numRecords": table.num_rows,
        "minValues": mins,
        "maxValues": maxs,
        "nullCount": nulls,
    }


def stats_json(table: pa.Table, num_indexed_cols: int = 32) -> str:
    return json.dumps(collect_stats(table, num_indexed_cols))


def write_parquet_file(
    table: pa.Table, abs_path: str, compression: str = "snappy"
) -> Tuple[int, int]:
    """Write one Parquet file; returns (size_bytes, mtime_ms)."""
    os.makedirs(os.path.dirname(abs_path), exist_ok=True)
    pq.write_table(table, abs_path, compression=compression)
    st = os.stat(abs_path)
    return st.st_size, int(st.st_mtime * 1000)


def read_parquet_files(
    abs_paths: Sequence[str],
    columns: Optional[Sequence[str]] = None,
    schema: Optional[pa.Schema] = None,
) -> List[pa.Table]:
    """Read data files; one table per file (callers attach partition values
    before concatenation)."""
    out = []
    for p in abs_paths:
        out.append(pq.read_table(p, columns=list(columns) if columns else None))
    return out
