"""Data-type breadth: every supported type through the full engine loop —
write → log round trip → read, stats capture, predicate pushdown, partition
values, and DML. The reference exercises this across many suites; here it
is one matrix per concern.
"""
import datetime
from decimal import Decimal

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log.deltalog import DeltaLog

D = datetime.date
TS = datetime.datetime

ALL_TYPES = pa.table({
    "b": pa.array([True, False, None]),
    "i8": pa.array([1, -2, None], pa.int8()),
    "i16": pa.array([300, -300, None], pa.int16()),
    "i32": pa.array([70_000, -70_000, None], pa.int32()),
    "i64": pa.array([2**40, -(2**40), None], pa.int64()),
    "f32": pa.array([1.5, -2.5, None], pa.float32()),
    "f64": pa.array([1e300, -1e-300, None], pa.float64()),
    "s": pa.array(["héllo", "", None]),
    "bin": pa.array([b"\x00\xff", b"", None], pa.binary()),
    "d": pa.array([D(2024, 2, 29), D(1970, 1, 1), None]),
    "ts": pa.array([TS(2024, 5, 1, 12, 30, 45, 123456), TS(1970, 1, 1), None],
                   pa.timestamp("us")),
    "dec": pa.array([Decimal("123.45"), Decimal("-0.01"), None],
                    pa.decimal128(10, 2)),
    "arr": pa.array([[1, 2], [], None], pa.list_(pa.int64())),
    "m": pa.array([{"k": 1}, {}, None], pa.map_(pa.string(), pa.int64())),
    "st": pa.array([{"x": 1, "y": "a"}, {"x": None, "y": None}, None],
                   pa.struct([("x", pa.int64()), ("y", pa.string())])),
})


def test_all_types_round_trip(tmp_table):
    t = DeltaTable.create(tmp_table, data=ALL_TYPES)
    DeltaLog.clear_cache()
    got = DeltaTable.for_path(tmp_table).to_arrow()
    assert got.num_rows == 3
    for col in ALL_TYPES.column_names:
        orig = ALL_TYPES.column(col).to_pylist()
        back = got.column(col).to_pylist()
        if col == "m":  # pyarrow renders maps as list-of-pairs; normalize both
            norm = lambda vs: [dict(v) if isinstance(v, list) else v for v in vs]
            orig, back = norm(orig), norm(back)
        assert back == orig, col


def test_all_types_survive_checkpoint(tmp_table):
    t = DeltaTable.create(tmp_table, data=ALL_TYPES)
    t.delta_log.checkpoint()
    DeltaLog.clear_cache()
    got = DeltaTable.for_path(tmp_table).to_arrow()
    assert got.num_rows == 3
    assert got.column("dec").to_pylist()[0] == Decimal("123.45")
    assert got.column("ts").to_pylist()[0] == TS(2024, 5, 1, 12, 30, 45, 123456)


def test_schema_json_round_trips_every_type(tmp_table):
    from delta_tpu.schema.types import schema_from_json

    t = DeltaTable.create(tmp_table, data=ALL_TYPES)
    meta = t.delta_log.update().metadata
    parsed = schema_from_json(meta.schema_string)
    assert parsed.to_json() == meta.schema.to_json()
    names = {f.name: f.data_type.simple_string() for f in parsed.fields}
    assert names["dec"] == "decimal(10,2)"
    assert names["arr"].startswith("array")
    assert names["st"].startswith("struct")


@pytest.mark.parametrize("col,pred,expect_ids", [
    ("i64", "i64 > 0", [0]),
    ("f64", "f64 < 0", [1]),
    ("s", "s = 'héllo'", [0]),
    ("d", "d >= '2024-01-01'", [0]),
    ("b", "b = true", [0]),
])
def test_predicates_per_type(tmp_table, col, pred, expect_ids):
    data = ALL_TYPES.append_column("rid", pa.array([0, 1, 2], pa.int64()))
    t = DeltaTable.create(tmp_table, data=data)
    got = t.to_arrow(filters=[pred])
    assert sorted(got.column("rid").to_pylist()) == expect_ids, pred


def test_stats_min_max_for_orderable_types(tmp_table):
    t = DeltaTable.create(tmp_table, data=ALL_TYPES)
    [f] = t.delta_log.update().all_files
    s = f.stats_dict()
    assert s["numRecords"] == 3
    assert s["minValues"]["i64"] == -(2**40)
    assert s["maxValues"]["i64"] == 2**40
    assert s["nullCount"]["s"] == 1
    # dates/timestamps serialize as ISO strings in stats JSON
    assert str(s["minValues"]["d"]).startswith("1970-01-01")
    # decimal bounds are deliberately absent (no always-safe JSON encoding);
    # nullCount is still recorded
    assert "dec" not in s["minValues"] and s["nullCount"]["dec"] == 1


def test_skipping_prunes_on_date_and_decimal(tmp_table):
    t = DeltaTable.create(tmp_table, data=pa.table({
        "d": pa.array([D(2023, 1, 1), D(2023, 6, 1)]),
        "x": pa.array([1, 2], pa.int64()),
    }))
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "d": pa.array([D(2024, 1, 1), D(2024, 6, 1)]),
        "x": pa.array([3, 4], pa.int64()),
    })).run()
    from delta_tpu.expr.parser import parse_predicate
    from delta_tpu.ops import pruning

    snap = t.delta_log.update()
    scan = pruning.files_for_scan(snap, [parse_predicate("d >= '2024-01-01'")])
    assert len(scan.files) == 1 < len(snap.all_files)


@pytest.mark.parametrize("value,part_dir", [
    (pa.array(["x y"]), "p=x y"),
    (pa.array([7], pa.int64()), "p=7"),
    (pa.array([D(2024, 5, 1)]), "p=2024-05-01"),
    (pa.array([True]), "p=true"),
])
def test_partition_values_per_type(tmp_table, value, part_dir):
    import os

    data = pa.table({"p": value, "x": pa.array([1], pa.int64())})
    t = DeltaTable.create(tmp_table, data=data, partition_columns=["p"])
    dirs = [d for d in os.listdir(tmp_table) if d.startswith("p=")]
    assert len(dirs) == 1
    got = t.to_arrow()
    assert got.column("p").to_pylist() == value.to_pylist()
    # partition pruning on the typed value
    lit = value[0].as_py()
    if isinstance(lit, bool):
        pred = f"p = {str(lit).lower()}"
    elif isinstance(lit, (int,)):
        pred = f"p = {lit}"
    else:
        pred = f"p = '{lit}'"
    assert t.to_arrow(filters=[pred]).num_rows == 1


def test_timestamp_literal_with_utc_offset(tmp_table):
    """Offset literals convert to UTC before comparing against the naive
    (UTC-convention) timestamp column."""
    t = DeltaTable.create(tmp_table, data=pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "ts": pa.array([TS(2024, 5, 1, 5, 0), TS(2024, 5, 1, 12, 0)],
                       pa.timestamp("us")),
    }))
    # 10:00+05:00 == 05:00 UTC -> matches exactly row 1
    got = t.to_arrow(filters=["ts = '2024-05-01T10:00:00+05:00'"])
    assert got.column("id").to_pylist() == [1]


def test_v2_checkpoint_with_decimal_column(tmp_table):
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"dec": pa.array([Decimal("1.10")], pa.decimal128(10, 2))}),
        configuration={"delta.checkpoint.writeStatsAsStruct": "true"},
    )
    t.delta_log.checkpoint()  # must not raise on decimal stats
    DeltaLog.clear_cache()
    assert DeltaTable.for_path(tmp_table).to_arrow().num_rows == 1


def test_dml_on_decimal_and_timestamp(tmp_table):
    t = DeltaTable.create(tmp_table, data=pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "dec": pa.array([Decimal("1.10"), Decimal("2.20")], pa.decimal128(10, 2)),
        "ts": pa.array([TS(2024, 1, 1), TS(2024, 6, 1)], pa.timestamp("us")),
    }))
    t.delete("ts < '2024-03-01'")
    got = t.to_arrow()
    assert got.column("id").to_pylist() == [2]
    assert got.column("dec").to_pylist() == [Decimal("2.20")]


def test_nested_struct_merge_values(tmp_table):
    st = pa.struct([("x", pa.int64()), ("y", pa.string())])
    t = DeltaTable.create(tmp_table, data=pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "s": pa.array([{"x": 1, "y": "a"}, {"x": 2, "y": "b"}], st),
    }))
    src = pa.table({
        "id": pa.array([2, 3], pa.int64()),
        "s": pa.array([{"x": 20, "y": "B"}, {"x": 30, "y": "C"}], st),
    })
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
     .when_matched_update_all().when_not_matched_insert_all().execute())
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert [r["s"] for r in got] == [
        {"x": 1, "y": "a"}, {"x": 20, "y": "B"}, {"x": 30, "y": "C"}
    ]


# -- char/varchar (CharVarcharUtils.scala semantics) ------------------------


def test_char_varchar_wire_form_and_roundtrip(tmp_table):
    """char/varchar declare as STRING + __CHAR_VARCHAR_TYPE_STRING field
    metadata on the wire; the declared type is recoverable."""
    from delta_tpu.schema.char_varchar import (
        CHAR_VARCHAR_TYPE_STRING_METADATA_KEY, raw_type,
    )
    from delta_tpu.schema.types import (
        CharType, LongType, StringType, StructType, VarcharType,
    )

    schema = (StructType().add("id", LongType()).add("c", CharType(4))
              .add("v", VarcharType(6)))
    t = DeltaTable.create(tmp_table, schema)
    stored = t.delta_log.update().metadata.schema
    by_name = {f.name: f for f in stored.fields}
    assert isinstance(by_name["c"].data_type, StringType)
    assert by_name["c"].metadata[CHAR_VARCHAR_TYPE_STRING_METADATA_KEY] == "char(4)"
    assert by_name["v"].metadata[CHAR_VARCHAR_TYPE_STRING_METADATA_KEY] == "varchar(6)"
    assert raw_type(by_name["c"]) == CharType(4)
    assert raw_type(by_name["v"]) == VarcharType(6)


def test_char_pads_and_varchar_rejects(tmp_table):
    from delta_tpu.schema.types import CharType, LongType, StructType, VarcharType
    from delta_tpu.utils.errors import InvariantViolationError

    schema = (StructType().add("id", LongType()).add("c", CharType(4))
              .add("v", VarcharType(3)))
    t = DeltaTable.create(tmp_table, schema)
    t.delta_log  # create ok
    from delta_tpu.commands.write import WriteIntoDelta

    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "c": pa.array(["ab", None], pa.string()),
        "v": pa.array(["xyz", "ab"], pa.string()),
    })).run()
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got[0]["c"] == "ab  "  # char pads to width
    assert got[1]["c"] is None    # nulls stay null
    assert got[0]["v"] == "xyz"   # varchar stores as-is within bound
    # varchar over the bound rejects
    with pytest.raises(InvariantViolationError, match="length limitation"):
        WriteIntoDelta(t.delta_log, "append", pa.table({
            "id": pa.array([3], pa.int64()),
            "v": pa.array(["toolong"], pa.string()),
            "c": pa.array(["a"], pa.string()),
        })).run()
    # char over the bound rejects too
    with pytest.raises(InvariantViolationError, match="length limitation"):
        WriteIntoDelta(t.delta_log, "append", pa.table({
            "id": pa.array([4], pa.int64()),
            "v": pa.array(["ok"], pa.string()),
            "c": pa.array(["abcde"], pa.string()),
        })).run()


def test_varchar_overlength_trailing_spaces_truncate_to_bound(tmp_table):
    """'ab   ' into varchar(4) stores 'ab  ' (truncated to EXACTLY the
    bound, like the reference's varcharTypeWriteSideCheck) — not the full
    rtrim 'ab', which would diverge stored lengths/equality from the
    reference format."""
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.schema.types import LongType, StructType, VarcharType

    schema = StructType().add("id", LongType()).add("v", VarcharType(4))
    t = DeltaTable.create(tmp_table, schema)
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([1, 2, 3], pa.int64()),
        "v": pa.array(["ab   ", "cdef ", "in"], pa.string()),
    })).run()
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got[0]["v"] == "ab  "   # 4 chars: truncated, not rtrimmed
    assert got[1]["v"] == "cdef"   # exactly at the bound after truncation
    assert got[2]["v"] == "in"     # within bound: untouched


def test_char_varchar_sql_create_and_enforce(tmp_path):
    from delta_tpu.sql.parser import execute_sql
    from delta_tpu.utils.errors import DeltaError

    path = str(tmp_path / "cv")
    execute_sql(f"CREATE TABLE delta.`{path}` (id BIGINT, c CHAR(3), v VARCHAR(5))")
    execute_sql(f"INSERT INTO delta.`{path}` VALUES (1, 'ab', 'hello')")
    t = execute_sql(f"SELECT c, v FROM delta.`{path}`")
    assert t.column("c").to_pylist() == ["ab "]
    with pytest.raises(DeltaError, match="length limitation"):
        execute_sql(f"INSERT INTO delta.`{path}` VALUES (2, 'ab', 'toolongg')")


# -- path-embedded time travel (DeltaTimeTravelSpec.scala:137) --------------


def test_path_at_version_identifier(tmp_table):
    import numpy as np

    t = DeltaTable.create(tmp_table, data=pa.table({
        "a": pa.array([1, 2], pa.int64())}))
    from delta_tpu.commands.write import WriteIntoDelta

    WriteIntoDelta(t.delta_log, "append",
                   pa.table({"a": pa.array([3], pa.int64())})).run()
    pinned = DeltaTable.for_path(f"{tmp_table}@v0")
    assert sorted(pinned.to_arrow().column("a").to_pylist()) == [1, 2]
    latest = DeltaTable.for_path(tmp_table)
    assert sorted(latest.to_arrow().column("a").to_pylist()) == [1, 2, 3]
    # explicit options override the pinned default
    assert sorted(pinned.to_arrow(version=1).column("a").to_pylist()) == [1, 2, 3]
    # SQL form
    from delta_tpu.sql.parser import execute_sql

    out = execute_sql(f"SELECT a FROM delta.`{tmp_table}@v0`")
    assert sorted(out.column("a").to_pylist()) == [1, 2]


def test_path_at_timestamp_identifier(tmp_table):
    import datetime as dt

    t = DeltaTable.create(tmp_table, data=pa.table({
        "a": pa.array([1], pa.int64())}))
    # timestamp far in the future resolves to the latest commit
    future = (dt.datetime.now(dt.timezone.utc) + dt.timedelta(days=1))
    stamp = future.strftime("%Y%m%d%H%M%S") + "000"
    pinned = DeltaTable.for_path(f"{tmp_table}@{stamp}")
    assert pinned.to_arrow().column("a").to_pylist() == [1]


def test_literal_at_path_wins_over_time_travel(tmp_path):
    # a directory literally named "t@v0" resolves as itself
    p = str(tmp_path / "t@v0")
    t = DeltaTable.create(p, data=pa.table({"a": pa.array([7], pa.int64())}))
    assert DeltaTable.for_path(p).to_arrow().column("a").to_pylist() == [7]


def test_pinned_handle_rejects_dml(tmp_table):
    from delta_tpu.utils.errors import DeltaAnalysisError

    DeltaTable.create(tmp_table, data=pa.table({"a": pa.array([1], pa.int64())}))
    pinned = DeltaTable.for_path(f"{tmp_table}@v0")
    with pytest.raises(DeltaAnalysisError, match="time-travelled"):
        pinned.delete("a > 0")
    with pytest.raises(DeltaAnalysisError, match="time-travelled"):
        pinned.update({"a": "2"})
    with pytest.raises(DeltaAnalysisError, match="time-travelled"):
        pinned.optimize()
    # reads still work
    assert pinned.to_arrow().num_rows == 1


def test_char_read_side_padding_matches_literals(tmp_table):
    """Reference parity (ApplyCharTypePadding): filters compare unpadded
    literals against stored padded char values."""
    from delta_tpu.schema.types import CharType, LongType, StructType

    schema = StructType().add("id", LongType()).add("c", CharType(5))
    t = DeltaTable.create(tmp_table, schema)
    from delta_tpu.commands.write import WriteIntoDelta

    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([1, 2, 3], pa.int64()),
        "c": pa.array(["ab", "cd", None], pa.string()),
    })).run()
    out = t.to_arrow(filters=["c = 'ab'"])
    assert out.column("id").to_pylist() == [1]
    out = t.to_arrow(filters=["c >= 'cd'"])
    assert out.column("id").to_pylist() == [2]
    out = t.to_arrow(filters=["c IN ('ab', 'cd')"])
    assert sorted(out.column("id").to_pylist()) == [1, 2]
    # DML sees padded semantics too
    t.update({"id": "id + 10"}, "c = 'ab'")
    got = dict(zip(t.to_arrow().column("c").to_pylist(),
                   t.to_arrow().column("id").to_pylist()))
    assert got["ab   "] == 11
    t.delete("c = 'cd'")
    assert sorted(t.to_arrow().column("id").to_pylist()) == [3, 11]


def test_char_varchar_trailing_spaces_trim_before_error(tmp_table):
    """Over-length values shed trailing spaces before judgment (the
    reference's write-side checks): right-padded feed data keeps working."""
    from delta_tpu.schema.types import CharType, LongType, StructType, VarcharType

    schema = (StructType().add("id", LongType()).add("c", CharType(3))
              .add("v", VarcharType(3)))
    t = DeltaTable.create(tmp_table, schema)
    from delta_tpu.commands.write import WriteIntoDelta

    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([1], pa.int64()),
        "c": pa.array(["ab    "], pa.string()),   # trims to 'ab', pads 'ab '
        "v": pa.array(["xyz   "], pa.string()),   # trims to 'xyz'
    })).run()
    row = t.to_arrow().to_pylist()[0]
    assert row["c"] == "ab " and row["v"] == "xyz"


def test_pinned_handle_rejects_write_and_pins_schema(tmp_table):
    from delta_tpu.utils.errors import DeltaAnalysisError

    t = DeltaTable.create(tmp_table, data=pa.table({"a": pa.array([1], pa.int64())}))
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.schema.types import StructField, LongType

    add_columns(t.delta_log, [StructField("b", LongType())])
    pinned = DeltaTable.for_path(f"{tmp_table}@v0")
    with pytest.raises(DeltaAnalysisError, match="time-travelled"):
        pinned.write(pa.table({"a": pa.array([9], pa.int64())}))
    assert pinned.version == 0
    assert [f.name for f in pinned.schema().fields] == ["a"]
    latest = DeltaTable.for_path(tmp_table)
    assert [f.name for f in latest.schema().fields] == ["a", "b"]
