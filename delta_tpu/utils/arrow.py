"""Small shared Arrow helpers used across layers (log, ops)."""
from __future__ import annotations

import pyarrow as pa

__all__ = ["one_chunk"]


def one_chunk(arr):
    """Collapse a (Chunked)Array to a single contiguous Array.

    ``combine_chunks`` may still return a ChunkedArray (0 or 1 chunks
    depending on version); normalize all the way down so callers can use
    buffer-level APIs and ``take`` results uniformly."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = (pa.concat_arrays(arr.chunks)
                   if arr.num_chunks != 1 else arr.chunk(0))
    return arr
