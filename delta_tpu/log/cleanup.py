"""Metadata (log) cleanup — delete expired commit/checkpoint files.

Reference: ``MetadataCleanup.scala:27-98`` + ``BufferingLogDeletionIterator``
in ``DeltaHistoryManager.scala``. A delta/checkpoint file is deletable when
it is older than the log retention period AND a later checkpoint exists
covering it. The cutoff is truncated to day granularity, and deletion never
breaks the monotonized-timestamp invariant: we only delete a prefix of
versions strictly below the last checkpoint whose file timestamps are below
the cutoff.
"""
from __future__ import annotations

import logging
from typing import List

from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.protocol import filenames
from delta_tpu.utils.config import DeltaConfigs, conf

logger = logging.getLogger(__name__)

__all__ = ["cleanup_expired_logs", "sweep_tmp_orphans"]

MS_PER_DAY = 86_400_000


def sweep_tmp_orphans(delta_log, now_ms: int) -> int:
    """Delete aged ``.{name}.{uuid}.tmp`` staging orphans from ``_delta_log``.

    A writer that dies between staging and publishing (LocalLogStore's
    write-temp-then-link, or a simulated ``crash_before_publish``) strands
    its temp file; nothing ever references it, but it accumulates forever.
    Only files older than ``delta.tpu.cleanup.tmpOrphanTtlMs`` go — a
    young ``.tmp`` may be an in-flight write of a live concurrent writer.
    """
    ttl = int(conf.get("delta.tpu.cleanup.tmpOrphanTtlMs"))
    cutoff = now_ms - ttl
    # dot-files sort before version digits, so the normal version-prefixed
    # listings never see them; list from "." to include them
    try:
        statuses = list(delta_log.store.list_from(f"{delta_log.log_path}/."))
    except FileNotFoundError:
        return 0
    deleted = 0
    for fs in statuses:
        name = fs.name
        if (name.startswith(".") and name.endswith(".tmp")
                and fs.modification_time <= cutoff):
            if delta_log.store.delete(fs.path):
                deleted += 1
    if deleted:
        logger.info("Swept %d orphaned .tmp files from %s", deleted, delta_log.log_path)
    return deleted


def cleanup_expired_logs(delta_log, snapshot) -> int:
    """Delete expired log files; returns number deleted."""
    retention_ms = DeltaConfigs.LOG_RETENTION.from_metadata(snapshot.metadata)
    now = delta_log.clock()
    # Day-truncated cutoff (MetadataCleanup.scala:91-97).
    cutoff = ((now - retention_ms) // MS_PER_DAY) * MS_PER_DAY

    swept = sweep_tmp_orphans(delta_log, now)

    # workload-journal segments age out on the same cadence as the rest of
    # the metadata cleanup (they are also swept inline at rotation, but a
    # table that STOPPED journaling must still shed its history — so the
    # sweep runs even when journaling is currently disabled; it is a no-op
    # listdir when the directory doesn't exist)
    from delta_tpu.obs import journal as journal_mod

    if "://" not in delta_log.log_path:
        journal_mod.sweep(journal_mod.journal_dir(delta_log.log_path))
        # dead distributed-execution leases age out here too — same
        # aged-orphan discipline as .tmp staging files; live hosts' leases
        # are spared by the shared journal liveness rule
        from delta_tpu.parallel import leases as leases_mod

        leases_mod.sweep_leases(delta_log.log_path)

    last_ckpt = ckpt_mod.read_last_checkpoint(delta_log.store, delta_log.log_path)
    if last_ckpt is None:
        return swept
    ckpt_version = last_ckpt.version

    prefix = f"{delta_log.log_path}/{filenames.check_version_prefix(0)}"
    try:
        statuses = list(delta_log.store.list_from(prefix))
    except FileNotFoundError:
        return swept

    # Candidate files: version < last checkpoint version, mtime <= cutoff.
    # Keep timestamps monotone: stop at the first file (by version) that is
    # too new — deleting around it would leave holes.
    by_version: dict = {}
    for fs in statuses:
        name = fs.name
        if filenames.is_delta_file(name) or filenames.is_checkpoint_file(name) or filenames.is_checksum_file(name):
            v = filenames.get_file_version(name)
            if v is not None:
                by_version.setdefault(v, []).append(fs)

    deletable: List = []
    for v in sorted(by_version):
        if v >= ckpt_version:
            break
        files = by_version[v]
        if all(f.modification_time <= cutoff for f in files):
            deletable.extend(files)
        else:
            break  # monotonicity: stop at first too-new version

    deleted = 0
    for fs in deletable:
        if delta_log.store.delete(fs.path):
            deleted += 1
    if deleted:
        logger.info("Deleted %d expired log files older than %d in %s", deleted, cutoff, delta_log.log_path)
    return deleted + swept
