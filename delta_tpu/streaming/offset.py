"""Streaming source offset — versioned JSON, table-identity checked.

Mirrors `sources/DeltaSourceOffset.scala` (sourceVersion=1): an offset is
``(reservoirVersion, index, isStartingVersion)`` where ``index`` points INTO
a commit's file list (admission control can split one commit across
micro-batches) and ``isStartingVersion`` marks offsets still streaming the
initial snapshot rather than the log tail.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict

from delta_tpu.utils.errors import DeltaIllegalStateError

__all__ = ["DeltaSourceOffset", "VERSION"]

VERSION = 1


@dataclass(frozen=True, order=True)
class DeltaSourceOffset:
    reservoir_version: int
    index: int
    is_starting_version: bool
    reservoir_id: str = ""

    def json(self) -> str:
        return json.dumps(
            {
                "sourceVersion": VERSION,
                "reservoirId": self.reservoir_id,
                "reservoirVersion": self.reservoir_version,
                "index": self.index,
                "isStartingVersion": self.is_starting_version,
            },
            separators=(",", ":"),
        )

    @staticmethod
    def from_json(s: str, expected_table_id: str = "") -> "DeltaSourceOffset":
        d: Dict[str, Any] = json.loads(s)
        sv = d.get("sourceVersion")
        if sv is None or sv > VERSION:
            raise DeltaIllegalStateError(f"Unsupported Delta source offset version: {sv}")
        rid = d.get("reservoirId", "")
        if expected_table_id and rid and rid != expected_table_id:
            raise DeltaIllegalStateError(
                f"Offset belongs to table {rid}, not {expected_table_id} — "
                "delete the streaming checkpoint if the table was recreated"
            )
        return DeltaSourceOffset(
            reservoir_version=int(d["reservoirVersion"]),
            index=int(d["index"]),
            is_starting_version=bool(d.get("isStartingVersion", False)),
            reservoir_id=rid,
        )
