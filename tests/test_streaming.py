"""Streaming source/sink suites.

Behavioral spec: `DeltaSourceSuite` / `DeltaSinkSuite` (SURVEY §4) — initial
snapshot serving, log tailing, admission control, hygiene checks, offset
restart, sink exactly-once.
"""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.streaming.offset import DeltaSourceOffset
from delta_tpu.streaming.query import StreamingQuery
from delta_tpu.streaming.sink import DeltaSink
from delta_tpu.streaming.source import DeltaSource
from delta_tpu.utils.errors import DeltaIllegalStateError


def write(log, data, mode="append", **kw):
    return WriteIntoDelta(log, mode, data, **kw).run()


def drain(source, start=None):
    """Pull every pending batch; returns list of non-empty id-lists."""
    out = []
    cur = start
    while True:
        anchor = cur if cur is not None else source.initial_offset()
        end = source.latest_offset(anchor)
        if end is None:
            return out, cur
        t = source.get_batch(cur, end)
        if t.num_rows:
            out.append(sorted(t.column("id").to_pylist()))
        cur = end


def test_source_initial_snapshot_then_tail(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [3]})
    source = DeltaSource(log)
    batches, cur = drain(source)
    assert batches == [[1, 2, 3]]  # initial snapshot in one batch
    # now tail new commits
    write(log, {"id": [4, 5]})
    batches, cur = drain(source, cur)
    assert batches == [[4, 5]]
    # nothing new -> no batch
    batches, _ = drain(source, cur)
    assert batches == []


def test_source_max_files_per_trigger(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(4):
        write(log, {"id": [i]})
    source = DeltaSource(log, max_files_per_trigger=2)
    batches, _ = drain(source)
    assert batches == [[0, 1], [2, 3]]


def test_source_max_bytes_always_admits_one(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, max_files_per_trigger=None, max_bytes_per_trigger=1)
    batches, _ = drain(source)
    # 1 byte cap still admits one file per trigger (no stall)
    assert batches == [[0], [1], [2]]


def test_source_starting_version_skips_snapshot(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    write(log, {"id": [2]})
    write(log, {"id": [3]})
    source = DeltaSource(log, starting_version=1)
    batches, _ = drain(source)
    assert batches == [[2, 3]]


def test_source_delete_fails_stream(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    source = DeltaSource(log)
    _, cur = drain(source)
    DeleteCommand(log, None).run()
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)


def test_source_ignore_deletes(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [3]})
    source = DeltaSource(log, ignore_deletes=True)
    _, cur = drain(source)
    DeleteCommand(log, None).run()
    write(log, {"id": [9]})
    batches, _ = drain(source, cur)
    assert batches == [[9]]


def test_source_update_requires_ignore_changes(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [1, 1]})
    source = DeltaSource(log)
    _, cur = drain(source)
    UpdateCommand(log, {"v": "2"}, condition="id = 1").run()
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)
    # with ignoreChanges the rewritten file is re-emitted
    source2 = DeltaSource(log, ignore_changes=True)
    _, cur2 = drain(source2)
    UpdateCommand(log, {"v": "3"}, condition="id = 1").run()
    batches, _ = drain(source2, cur2)
    assert batches == [[1, 2]]  # whole rewritten file re-emitted


def test_source_schema_change_fails(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    source = DeltaSource(log)
    _, cur = drain(source)
    write(log, {"id": [2], "extra": ["x"]}, merge_schema=True)
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)


def test_offset_json_roundtrip_and_table_id_check():
    off = DeltaSourceOffset(7, 3, True, "tbl-1")
    back = DeltaSourceOffset.from_json(off.json(), "tbl-1")
    assert back == off
    with pytest.raises(DeltaIllegalStateError):
        DeltaSourceOffset.from_json(off.json(), "other-table")


def test_sink_exactly_once(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(log, query_id="q1")
    assert sink.add_batch(0, {"id": [1]}) is True
    assert sink.add_batch(0, {"id": [1]}) is False  # replay skipped
    assert sink.add_batch(1, {"id": [2]}) is True
    assert sorted(scan_to_table(log.update()).column("id").to_pylist()) == [1, 2]


def test_sink_complete_mode(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(log, query_id="q1", output_mode="complete")
    sink.add_batch(0, {"id": [1, 2]})
    sink.add_batch(1, {"id": [9]})
    assert scan_to_table(log.update()).column("id").to_pylist() == [9]


def test_query_end_to_end_and_restart(tmp_table, tmp_path):
    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1, 2]})

    def run_query():
        dst_log = DeltaLog.for_table(dst_path)
        source = DeltaSource(src_log, max_files_per_trigger=1)
        q = StreamingQuery(source, DeltaSink(dst_log, query_id="qx"), ckpt)
        return q.process_all_available()

    assert run_query() == 1
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2]
    # new upstream commits; a fresh query object resumes from the checkpoint
    write(src_log, {"id": [3]})
    write(src_log, {"id": [4]})
    # one empty snapshot→tail transition batch + one file per trigger
    assert run_query() == 3
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2, 3, 4]
    # drained: no more batches, no duplicates
    assert run_query() == 0
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2, 3, 4]


def test_query_recovers_unfinished_batch(tmp_table, tmp_path):
    import os

    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1]})

    source = DeltaSource(src_log)
    dst_log = DeltaLog.for_table(dst_path)
    q = StreamingQuery(source, DeltaSink(dst_log, query_id="qy"), ckpt)
    q.process_all_available()
    # simulate crash after writing the offset but before running batch 1
    write(src_log, {"id": [2]})
    end = source.latest_offset(q._read_offset(0))
    q._write_offset(1, end)
    # restart: the planned batch must run exactly once
    q2 = StreamingQuery(
        DeltaSource(src_log), DeltaSink(dst_log, query_id="qy"), ckpt
    )
    ran = q2.process_all_available()
    assert ran == 2  # recovered transition batch + the data batch
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2]


# -- review regressions -----------------------------------------------------


def test_source_rearrange_only_commit_does_not_spin(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, ignore_changes=True)
    _, cur = drain(source)
    OptimizeCommand(log).run()  # dataChange=False commit
    # the offset advances past the data-less commit exactly once, then stops
    end = source.latest_offset(cur)
    if end is not None:
        assert source.latest_offset(end) is None
        assert source.get_batch(cur, end).num_rows == 0


def test_query_recovery_of_initial_snapshot_batch(tmp_table, tmp_path):
    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1, 2]})

    # plan batch 0 (initial snapshot) but crash before running it
    source = DeltaSource(src_log)
    q = StreamingQuery(source, DeltaSink(DeltaLog.for_table(dst_path), query_id="qz"), ckpt)
    end0 = source.latest_offset(source.initial_offset())
    q._write_offset(0, end0)
    # upstream moves on before the restart
    write(src_log, {"id": [3]})
    q2 = StreamingQuery(
        DeltaSource(src_log), DeltaSink(DeltaLog.for_table(dst_path), query_id="qz"), ckpt
    )
    q2.process_all_available()
    got = sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    )
    assert got == [1, 2, 3]  # snapshot rows must NOT be lost
