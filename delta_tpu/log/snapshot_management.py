"""Log-segment computation and snapshot updates.

Reference: ``SnapshotManagement.scala:44-373``. Given a log directory, work
out which checkpoint parts + contiguous delta files define a version, verify
contiguity/completeness, and build Snapshots — including time travel
(``getSnapshotAt``) and cheap ``update()`` with early exit when the segment
is unchanged.
"""
from __future__ import annotations

from typing import List, Optional, TYPE_CHECKING

from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.log.checkpoints import CheckpointInstance
from delta_tpu.log.snapshot import LogSegment, Snapshot
from delta_tpu.protocol import filenames
from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.errors import (
    DeltaIllegalStateError,
    VersionNotFoundError,
    versions_not_contiguous,
)

if TYPE_CHECKING:
    from delta_tpu.log.deltalog import DeltaLog

__all__ = ["list_log_files", "get_log_segment_for_version", "verify_delta_versions"]


def list_log_files(store: LogStore, log_path: str, start_version: int) -> List[FileStatus]:
    """List delta/checkpoint files with version >= start_version
    (``SnapshotManagement.scala:57-65``)."""
    prefix = f"{log_path}/{filenames.check_version_prefix(start_version)}"
    out: List[FileStatus] = []
    try:
        for fs in store.list_from(prefix):
            if filenames.is_delta_file(fs.name) or filenames.is_checkpoint_file(fs.name):
                out.append(fs)
    except FileNotFoundError:
        return []
    return out


def verify_delta_versions(versions: List[int], expected_start: Optional[int] = None,
                          expected_end: Optional[int] = None) -> None:
    """Contiguity check (``SnapshotManagement.scala:365-372``)."""
    if versions:
        if versions != list(range(versions[0], versions[-1] + 1)):
            raise versions_not_contiguous(versions)
    if expected_start is not None and (not versions or versions[0] != expected_start):
        raise DeltaIllegalStateError(
            f"Did not get the first delta file version {expected_start} to compute snapshot"
        )
    if expected_end is not None and (not versions or versions[-1] != expected_end):
        raise DeltaIllegalStateError(
            f"Did not get the last delta file version {expected_end} to compute snapshot"
        )


def get_log_segment_for_version(
    store: LogStore,
    log_path: str,
    version_to_load: Optional[int] = None,
    start_checkpoint: Optional[int] = None,
    excluded_checkpoints: frozenset = frozenset(),
) -> Optional[LogSegment]:
    """Compute the LogSegment for a version (latest if None), starting the
    listing at ``start_checkpoint`` (from ``_last_checkpoint``) when given
    (``SnapshotManagement.scala:82-179``). Returns None when the directory
    has no delta files at all (uninitialized table).
    ``excluded_checkpoints``: checkpoint versions known corrupt — skipped
    during selection (decode-failure recovery, `snapshot.py:_columnar`)."""
    if version_to_load is not None and start_checkpoint is not None and start_checkpoint > version_to_load:
        start_checkpoint = None  # pointer is past the requested version: list from scratch
    if excluded_checkpoints and start_checkpoint in excluded_checkpoints:
        start_checkpoint = None
    list_start = start_checkpoint or 0
    files = [f for f in list_log_files(store, log_path, list_start) if f.size > 0 or filenames.is_delta_file(f.name)]

    if version_to_load is not None:
        files = [f for f in files if (filenames.get_file_version(f.name) or 0) <= version_to_load]

    if not files:
        if start_checkpoint:
            # _last_checkpoint points at a vanished checkpoint: re-list from 0
            # (SnapshotManagement.scala:118-126).
            return get_log_segment_for_version(
                store, log_path, version_to_load, None,
                excluded_checkpoints=excluded_checkpoints,
            )
        return None

    checkpoint_candidates: List[CheckpointInstance] = []
    checkpoint_statuses = {}
    deltas: List[FileStatus] = []
    for f in files:
        if filenames.is_checkpoint_file(f.name) and f.size > 0:
            v = filenames.checkpoint_version(f.name)
            if v in excluded_checkpoints:
                continue
            part = filenames.checkpoint_part(f.name)
            inst = CheckpointInstance(v, part[1] if part else None)
            checkpoint_candidates.append(inst)
            checkpoint_statuses.setdefault(inst, []).append(f)
        elif filenames.is_delta_file(f.name):
            deltas.append(f)

    latest_checkpoint = ckpt_mod.latest_complete_checkpoint(
        checkpoint_candidates, not_later_than=version_to_load
    )

    if latest_checkpoint is not None:
        ckpt_version = latest_checkpoint.version
        ckpt_files = sorted(checkpoint_statuses[latest_checkpoint], key=lambda s: s.name)
        deltas_after = [f for f in deltas if filenames.delta_version(f.name) > ckpt_version]
        versions = sorted(filenames.delta_version(f.name) for f in deltas_after)
        deltas_after.sort(key=lambda f: filenames.delta_version(f.name))
        if versions:
            verify_delta_versions(versions, expected_start=ckpt_version + 1)
            new_version = versions[-1]
        else:
            new_version = ckpt_version
        if version_to_load is not None and new_version != version_to_load:
            # requested version not reachable
            raise DeltaIllegalStateError(
                f"Trying to load version {version_to_load} but log only goes to {new_version}"
            )
        last_ts = deltas_after[-1].modification_time if deltas_after else (
            ckpt_files[-1].modification_time if ckpt_files else 0
        )
        return LogSegment(log_path, new_version, deltas_after, ckpt_files, ckpt_version, last_ts)

    # No complete checkpoint in the listing. If we trusted a _last_checkpoint
    # pointer, it lied (checkpoint deleted/corrupt): recover by re-listing the
    # whole log from 0 (``SnapshotManagement.scala:118-126``).
    if start_checkpoint:
        return get_log_segment_for_version(
            store, log_path, version_to_load, None,
            excluded_checkpoints=excluded_checkpoints,
        )
    deltas.sort(key=lambda f: filenames.delta_version(f.name))
    versions = [filenames.delta_version(f.name) for f in deltas]
    if not versions:
        return None
    verify_delta_versions(versions, expected_start=0, expected_end=version_to_load)
    return LogSegment(
        log_path, versions[-1], deltas, [], None, deltas[-1].modification_time
    )


def get_snapshot_at(delta_log: "DeltaLog", version: int) -> Snapshot:
    """Time travel to ``version`` (``SnapshotManagement.scala:342-360``)."""
    current = delta_log.unsafe_volatile_snapshot
    if current is not None and current.version == version:
        return current
    if version < 0 or (current is not None and version > current.version):
        # out-of-range asks get the user-facing time-travel error
        # (``DeltaErrors.versionNotExistException``), not a contiguity error
        latest = delta_log.update().version
        if version < 0 or version > latest:
            raise VersionNotFoundError(version, 0, latest)
    start_ckpt = None
    found = ckpt_mod.find_last_complete_checkpoint_before(
        delta_log.store, delta_log.log_path, version + 1
    )
    if found is not None and found.version <= version:
        start_ckpt = found.version
    segment = get_log_segment_for_version(
        delta_log.store, delta_log.log_path, version_to_load=version, start_checkpoint=start_ckpt
    )
    if segment is None:
        raise VersionNotFoundError(version, 0, current.version if current else -1)
    return Snapshot(delta_log, segment.version, segment)
