"""User-facing timestamp-option parsing, shared by every surface that takes
a point in time (time-travel reads, streaming ``startingTimestamp``,
RESTORE ... TO TIMESTAMP AS OF): epoch milliseconds (int/float/numeric
string) or ISO-8601 ('2024-05-01 12:00:00', naive = UTC)."""
from __future__ import annotations

from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["timestamp_option_to_ms", "iso_to_naive_utc", "iso_to_date"]


def iso_to_naive_utc(s: str):
    """ISO-8601 → naive datetime in UTC (the engine's timestamp convention:
    naive values ARE UTC). 'Z' and explicit offsets convert to UTC before
    the tzinfo is dropped — one parser for every call site."""
    import datetime as _dt

    out = _dt.datetime.fromisoformat(
        str(s).strip().replace(" ", "T").replace("Z", "+00:00")
    )
    if out.tzinfo is not None:
        out = out.astimezone(_dt.timezone.utc).replace(tzinfo=None)
    return out


def iso_to_date(s: str):
    import datetime as _dt

    return _dt.date.fromisoformat(str(s).strip()[:10])


def timestamp_option_to_ms(ts) -> int:
    if isinstance(ts, bool):
        raise errors.invalid_timestamp_format(ts)
    if isinstance(ts, (int, float)):
        return int(ts)
    s = str(ts).strip()
    if s.lstrip("-").isdigit():
        return int(s)
    import datetime as _dt

    try:
        out = iso_to_naive_utc(s)
    except ValueError as e:
        raise errors.invalid_timestamp_format(ts, e)
    return int(out.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)
