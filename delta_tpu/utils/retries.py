"""Shared retry policy for transient storage failures.

Generalized from the private copy that lived in ``storage/http_store.py``:
one :class:`RetryPolicy` (bounded exponential backoff + a **total deadline**
so a flapping store fails in bounded time), one transient-vs-permanent
classifier (:func:`is_transient`), and one driver (:func:`call_with_retries`)
that only ever wraps *idempotent* operations — reads, listings, existence
probes, overwrite-PUTs of deterministic content. The commit create-if-absent
is NEVER driven through here: retrying it blind could double-commit; the
ambiguous-outcome path lives in ``txn/transaction.py`` reconciliation
instead (≈ the reference's manual-retry guidance around
``HDFSLogStore.scala:46-90``).

Telemetry: every retry bumps ``storage.retry.attempts``; giving up bumps
``storage.retry.exhausted`` and raises the final error through a
``delta.storage.retry.exhausted`` span so the obs flight recorder
(``delta_tpu/obs/flight_recorder.py``) captures an incident when configured.
"""
from __future__ import annotations

import errno
import http.client
import socket
import time
from dataclasses import dataclass
from typing import Callable, Optional, TypeVar

from delta_tpu.utils.errors import DeltaIOError

__all__ = [
    "RetryPolicy",
    "TransientIOError",
    "is_transient",
    "call_with_retries",
]

T = TypeVar("T")


class TransientIOError(DeltaIOError):
    """An IO failure the caller may retry (connection reset, throttle,
    injected fault). Permanent failures stay plain :class:`DeltaIOError`."""


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff for transient storage failures.

    ``deadline_s`` bounds the TOTAL wall time spent across attempts and
    sleeps: a store that flaps forever fails in ``deadline_s``, not
    ``max_attempts * max_delay_s`` (which at the defaults would be 4x
    longer). ``timeout_s`` is the per-request socket timeout HTTP stores
    apply to each individual attempt.
    """

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    timeout_s: float = 30.0
    deadline_s: float = 60.0

    def delay(self, attempt: int) -> float:
        return min(self.base_delay_s * (2 ** attempt), self.max_delay_s)

    def give_up(self, attempt: int, start_monotonic: float,
                clock: Callable[[], float] = time.monotonic) -> bool:
        """True when no further attempt should be made: either the attempt
        budget is spent or sleeping for the next backoff would cross the
        total deadline."""
        if attempt + 1 >= self.max_attempts:
            return True
        if self.deadline_s and (
            clock() - start_monotonic + self.delay(attempt) >= self.deadline_s
        ):
            return True
        return False


#: errno values worth retrying on a local filesystem: transient kernel/IO
#: conditions, not programming or layout errors.
_TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EBUSY, errno.EINTR,
    errno.ETIMEDOUT, errno.ENETDOWN, errno.ENETUNREACH, errno.ECONNRESET,
})

#: OSError subclasses that are *semantic* results, never transient faults.
_PERMANENT_OSERRORS = (
    FileNotFoundError, FileExistsError, IsADirectoryError,
    NotADirectoryError, PermissionError,
)


def is_transient(exc: BaseException) -> bool:
    """Is ``exc`` a failure that may succeed on retry?

    FileNotFound/FileExists are load-bearing protocol signals (missing
    version / OCC conflict) and must surface immediately; a plain
    :class:`DeltaIOError` is a store's *final* verdict (e.g. the HTTP store
    after its own internal retries) and is not retried again here.
    """
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, _PERMANENT_OSERRORS):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError,
                        socket.timeout, http.client.HTTPException)):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def call_with_retries(
    fn: Callable[[], T],
    policy: Optional[RetryPolicy] = None,
    op_name: str = "storage.op",
    classify: Callable[[BaseException], bool] = is_transient,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``fn`` retrying transient failures under ``policy``.

    Only for idempotent operations — see the module docstring. Exhaustion
    re-raises the last error through a telemetry span so the flight
    recorder can write an incident.
    """
    from delta_tpu.utils import telemetry

    policy = policy or RetryPolicy()
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not classify(e):
                raise
            if policy.give_up(attempt, start):
                telemetry.bump_counter("storage.retry.exhausted")
                # raise through a span: the failure hook chain (incl. the
                # obs flight recorder, when configured) sees the give-up
                with telemetry.record_operation(
                    "delta.storage.retry.exhausted",
                    {"op": op_name, "attempts": attempt + 1,
                     "elapsedS": round(time.monotonic() - start, 3)},
                ):
                    raise
            telemetry.bump_counter("storage.retry.attempts")
            sleep(policy.delay(attempt))
            attempt += 1
