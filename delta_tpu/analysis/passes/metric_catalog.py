"""Metric-catalog pass — migrated from ``tests/test_telemetry.py``.

Every constant-string metric call site engine-wide must resolve to the
``obs/metric_names.py`` catalog, so dashboards never chase stringly-typed
drift. Coverage is identical to the old test-embedded lints:

``metric-uncataloged``
    * a ``set_gauge`` name missing from ``GAUGES``;
    * a ``bump_counter`` name from ``obs/`` or the obs-feed namespaces
      (``obs.``/``maintenance.``/``storage.retry.``/``faults.``/
      ``merge.device.``/``merge.keyCache.`` and
      ``commit.conflicts``/``commit.reconciled``) missing from
      ``COUNTERS``;
    * any other constant ``bump_counter`` name missing from
      ``COUNTERS ∪ ENGINE_COUNTERS`` (the inverse pass);
    * an ``observe`` name missing from ``HISTOGRAMS``.
    Dynamic f-string families (``logstore.{op}.*``) are out of scope by
    construction. Beyond string literals, a first argument that is a bare
    name resolves when the file binds it exactly once, as a module-level
    constant string — the ``_METRIC = "x.y"; bump_counter(_METRIC)`` idiom
    no longer hides a call site from the catalog.
``metric-overlap``
    A counter cataloged in both ``COUNTERS`` and ``ENGINE_COUNTERS``.

The catalog is read from the analyzed AST of ``obs/metric_names.py``
(frozenset literals) — fixtures supply a synthetic one; with no catalog in
context the pass is silent.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from delta_tpu.analysis.core import AnalysisContext, AnalysisPass, Finding
from delta_tpu.analysis.modgraph import terminal_name

__all__ = ["MetricCatalogPass", "catalog_sets"]

OBS_FEED_PREFIXES = ("obs.", "maintenance.", "storage.retry.", "faults.",
                     "merge.device.", "merge.keyCache.")
OBS_FEED_NAMES = ("commit.conflicts", "commit.reconciled")


def catalog_sets(sf) -> Optional[Dict[str, Dict[str, int]]]:
    """``{set_name: {entry: lineno}}`` for the frozenset catalogs in the
    metric-names module, or None when none are present."""
    out: Dict[str, Dict[str, int]] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name) or t.id not in (
                "GAUGES", "COUNTERS", "ENGINE_COUNTERS", "HISTOGRAMS"):
            continue
        v = node.value
        if not (isinstance(v, ast.Call)
                and terminal_name(v.func) == "frozenset" and v.args
                and isinstance(v.args[0], ast.Set)):
            continue
        entries: Dict[str, int] = {}
        for elt in v.args[0].elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                entries[elt.value] = elt.lineno
        out[t.id] = entries
    return out or None


def _module_str_consts(sf) -> Dict[str, str]:
    """Identifiers that resolve to exactly one value file-wide: bound once
    in the whole tree (no parameter, loop, or nested-function shadowing —
    counting bindings sidesteps scope analysis), and that binding is a
    simple module-level ``NAME = "literal"``."""
    stores: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            stores[node.id] = stores.get(node.id, 0) + 1
        elif isinstance(node, ast.arg):
            stores[node.arg] = stores.get(node.arg, 0) + 1
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            # declared rebindable from another scope: opaque, never resolve
            for n in node.names:
                stores[n] = stores.get(n, 0) + 2
    out: Dict[str, str] = {}
    for stmt in sf.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            name, value = stmt.targets[0].id, stmt.value
        elif (isinstance(stmt, ast.AnnAssign)
              and isinstance(stmt.target, ast.Name)
              and stmt.value is not None):
            name, value = stmt.target.id, stmt.value
        else:
            continue
        if (isinstance(value, ast.Constant) and isinstance(value.value, str)
                and stores.get(name) == 1):
            out[name] = value.value
    return out


def _const_metric_calls(sf, fn_name: str) -> List[Tuple[str, int]]:
    out = []
    consts: Optional[Dict[str, str]] = None  # resolved on first Name arg
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if terminal_name(node.func) != fn_name or not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
        elif isinstance(arg, ast.Name):
            if consts is None:
                consts = _module_str_consts(sf)
            if arg.id in consts:
                out.append((consts[arg.id], node.lineno))
    return out


class MetricCatalogPass(AnalysisPass):
    name = "metric-catalog"
    description = ("constant-name set_gauge/bump_counter/observe call "
                   "sites resolve to obs/metric_names.py")
    rules = ("metric-uncataloged", "metric-overlap")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        cat_file = ctx.find_suffix("obs/metric_names.py")
        sets = catalog_sets(cat_file) if cat_file is not None else None
        if sets is None:
            return []
        gauges = frozenset(sets.get("GAUGES", {}))
        counters = frozenset(sets.get("COUNTERS", {}))
        engine_counters = frozenset(sets.get("ENGINE_COUNTERS", {}))
        histograms = frozenset(sets.get("HISTOGRAMS", {}))
        out: List[Finding] = []
        for name in sorted(counters & engine_counters):
            out.append(Finding(
                "metric-overlap", cat_file.rel,
                sets["COUNTERS"][name],
                f"counter '{name}' is cataloged in both COUNTERS and "
                f"ENGINE_COUNTERS"))
        for sf in ctx.files:
            in_obs = "/obs/" in f"/{sf.rel}"
            for name, line in _const_metric_calls(sf, "set_gauge"):
                if name not in gauges:
                    out.append(Finding(
                        "metric-uncataloged", sf.rel, line,
                        f"gauge '{name}' is missing from "
                        f"obs/metric_names.GAUGES"))
            for name, line in _const_metric_calls(sf, "bump_counter"):
                obs_feed = (name.startswith(OBS_FEED_PREFIXES)
                            or name in OBS_FEED_NAMES)
                if (in_obs or obs_feed) and name not in counters:
                    out.append(Finding(
                        "metric-uncataloged", sf.rel, line,
                        f"obs-layer counter '{name}' is missing from "
                        f"obs/metric_names.COUNTERS"))
                elif name not in counters | engine_counters:
                    out.append(Finding(
                        "metric-uncataloged", sf.rel, line,
                        f"counter '{name}' is missing from "
                        f"obs/metric_names.py (COUNTERS/ENGINE_COUNTERS)"))
            for name, line in _const_metric_calls(sf, "observe"):
                if name not in histograms:
                    out.append(Finding(
                        "metric-uncataloged", sf.rel, line,
                        f"histogram '{name}' is missing from "
                        f"obs/metric_names.HISTOGRAMS"))
        return out
