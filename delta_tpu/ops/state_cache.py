"""Device-resident snapshot state: table metadata cached in HBM.

The reference caches reconstructed state as a Spark-memory Dataset
(`util/StateCache.scala:34-110` backing `Snapshot.scala:88-111`), so repeat
queries replay nothing. The TPU-native equivalent keeps the *scan-planning
lanes* of the reconciled state — per-file min/max/nullCount stats, sizes,
aliveness — resident in HBM, keyed by table, and updates them incrementally
as the log tails forward: each new commit appends a handful of rows
device-side (one small upload + one scatter/slice kernel), so steady-state
queries pay **zero bulk upload**.

Why this is the piece that makes the chip win: on any link (PCIe or
tunneled), re-uploading O(files) state per query prices the device out of
interactive planning; from residency, a *batch* of N predicates over F files
and C stat columns is one dispatch reading N·F·C lanes from HBM (~800 GB/s)
against a host evaluator bound by DRAM (~10 GB/s single-core), and one
small packed block-bitmap download finished exactly on the host mirrors
(coarse-fine; see ``_plan_device``).

Precision: stats lanes are stored as float32 with **conservative rounding**
— min lanes round toward -inf, max lanes toward +inf, and query bounds round
outward the same way (`_f32_down`/`_f32_up`) — so a float32 verdict can only
*keep* extra files, never drop a matching one. NaN = missing stat = keep.
The skipping rewrite only ever tests ``min.c`` against upper bounds and
``max.c`` against lower bounds (`ops/pruning.skipping_predicate`), which is
what makes one rounding direction per lane sufficient.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from delta_tpu.expr import ir
from delta_tpu.utils.config import conf

__all__ = [
    "ResidentState", "DeviceStateCache", "PlanResult", "extract_ranges",
    "RangeSet",
]


def _f32_down(x: np.ndarray) -> np.ndarray:
    """float64 → float32 rounded toward -inf (result <= x). NaN passes."""
    with np.errstate(invalid="ignore", over="ignore"):
        f = x.astype(np.float32)
        bump = f.astype(np.float64) > x
    if bump.any():
        f = f.copy()
        f[bump] = np.nextafter(f[bump], np.float32(-np.inf))
    return f


def _f32_up(x: np.ndarray) -> np.ndarray:
    """float64 → float32 rounded toward +inf (result >= x). NaN passes."""
    with np.errstate(invalid="ignore", over="ignore"):
        f = x.astype(np.float32)
        bump = f.astype(np.float64) < x
    if bump.any():
        f = f.copy()
        f[bump] = np.nextafter(f[bump], np.float32(np.inf))
    return f


def _next_pow2(n: int, floor: int = 1024) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


# -- range extraction from skipping predicates ------------------------------


@dataclass
class RangeSet:
    """One query as per-column bounds: keep file iff for every column c,
    ``max.c >= lo[c] AND min.c <= hi[c]`` (NaN bound = unconstrained).
    ``verdict`` short-circuits structural cases: 'empty' (matches nothing),
    'all' (prunes nothing)."""

    lo: np.ndarray  # float64, len C, NaN = -inf
    hi: np.ndarray  # float64, len C, NaN = +inf
    verdict: Optional[str] = None  # None | 'empty' | 'all'
    # True when the lowering lost nothing: no strict comparison was relaxed
    # to non-strict, so the range verdict EQUALS the exact evaluator's
    exact: bool = True


def _part_lane_rows(codes: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Partition lane rows from int32 codes: min=max=code; null (-1) becomes
    the inverted range (+inf, -inf) so every bounded query prunes it exactly
    (NaN would mean 'missing stat: keep' — the wrong semantics for a KNOWN
    null partition value)."""
    f = codes.astype(np.float64)
    lo = np.where(codes >= 0, f, np.inf)
    hi = np.where(codes >= 0, f, -np.inf)
    return lo, hi


@dataclass
class PartLane:
    """One partition column's dictionary lane: codes are ranks in VALUE
    order at build time (typed order for numeric/temporal columns, code-
    point order for strings), so value ranges lower to code ranges. A tail
    extension that arrives out of order appends its code at the end and
    clears ``sorted`` — equality lowering survives, range lowering stops
    until the entry rebuilds."""

    values: List[str]  # code -> raw partition string
    parsed: Optional[np.ndarray]  # typed sort keys (float64) or None (lex)
    code_of: Dict[str, int]
    sorted: bool = True
    dt: object = None  # DataType used to parse (set iff parsed is not None)

    def eq_code(self, lit) -> Optional[int]:
        """Code whose value equals the literal; -1 = no file has it; None =
        the literal isn't comparable against this lane."""
        if self.parsed is not None:
            if isinstance(lit, bool) or not isinstance(lit, (int, float)):
                return None
            v = float(lit)
            if self.sorted:
                i = int(np.searchsorted(self.parsed, v))
                return i if i < len(self.parsed) and self.parsed[i] == v else -1
            hits = np.nonzero(self.parsed == v)[0]
            return int(hits[0]) if len(hits) else -1
        if not isinstance(lit, str):
            return None
        c = self.code_of.get(lit)
        return c if c is not None else -1

    def bound_code(self, lit, op) -> Optional[Tuple[float, float]]:
        """(lo, hi) code bounds (NaN = unbounded) for `col <op> lit`, or
        None when not lowerable (unsorted lane / type mismatch)."""
        import bisect

        if not self.sorted:
            return None
        if self.parsed is not None:
            if isinstance(lit, bool) or not isinstance(lit, (int, float)):
                return None
            v = float(lit)
            left = int(np.searchsorted(self.parsed, v, side="left"))
            right = int(np.searchsorted(self.parsed, v, side="right"))
        else:
            if not isinstance(lit, str):
                return None
            left = bisect.bisect_left(self.values, lit)
            right = bisect.bisect_right(self.values, lit)
        if op == "lt":
            return (np.nan, left - 1 + 0.0)  # codes < first value >= lit
        if op == "le":
            return (np.nan, right - 1 + 0.0)
        if op == "gt":
            return (right + 0.0, np.nan)
        if op == "ge":
            return (left + 0.0, np.nan)
        return None


def _intersect_ranges(a: RangeSet, b: RangeSet) -> RangeSet:
    """Conjunction of two boxes: per-column max of lows / min of highs
    (NaN = unbounded, so fmax/fmin ignore it)."""
    if a.verdict == "empty" or b.verdict == "empty":
        return RangeSet(a.lo, a.hi, verdict="empty",
                        exact=a.exact and b.exact)
    if a.verdict == "all":
        return RangeSet(b.lo, b.hi, verdict=b.verdict,
                        exact=a.exact and b.exact)
    if b.verdict == "all":
        return RangeSet(a.lo, a.hi, verdict=a.verdict,
                        exact=a.exact and b.exact)
    return RangeSet(np.fmax(a.lo, b.lo), np.fmin(a.hi, b.hi),
                    exact=a.exact and b.exact)


def extract_range_union(
    pred: ir.Expression,
    columns: Sequence[str],
    part_info: Optional[Dict[str, PartLane]] = None,
    max_terms: int = 8,
    str_lanes: Optional[frozenset] = None,
) -> Optional[List[RangeSet]]:
    """Lower a rewritten skipping predicate to a UNION of per-column range
    boxes (limited DNF): OR branches union, AND distributes (capped at
    ``max_terms``), and partition IN-lists lower to runs of consecutive
    dictionary codes. Every term exact ⇒ the union equals the exact
    evaluator's keep-set (terms may overlap; callers union row sets).
    None when any branch doesn't lower — the caller falls back."""
    t = type(pred)
    one = extract_ranges(pred, columns, part_info, str_lanes)
    if one is not None:
        return [one]
    if t is ir.Or:
        l = extract_range_union(pred.left, columns, part_info, max_terms,
                                str_lanes)
        if l is None:
            return None
        r = extract_range_union(pred.right, columns, part_info, max_terms,
                                str_lanes)
        if r is None or len(l) + len(r) > max_terms:
            return None
        return l + r
    if t is ir.And:
        l = extract_range_union(pred.left, columns, part_info, max_terms,
                                str_lanes)
        if l is None:
            return None
        r = extract_range_union(pred.right, columns, part_info, max_terms,
                                str_lanes)
        if r is None or len(l) * len(r) > max_terms:
            return None
        return [_intersect_ranges(a, b) for a in l for b in r]
    if (t is ir.In and part_info and isinstance(pred.value, ir.Column)):
        pmap = {c.lower(): c for c in part_info}
        key = pmap.get(pred.value.name.lower())
        if key is None:
            return None
        part = part_info[key]
        i = list(columns).index(key)
        codes = []
        for o in pred.options:
            if not isinstance(o, ir.Literal) or o.value is None:
                return None
            c = part.eq_code(o.value)
            if c is None:
                return None
            if c >= 0:
                codes.append(c)
        if not codes:
            e = RangeSet(np.full(len(columns), np.nan),
                         np.full(len(columns), np.nan), verdict="empty")
            return [e]
        codes = sorted(set(codes))
        runs: List[Tuple[int, int]] = []
        for c in codes:
            if runs and c == runs[-1][1] + 1:
                runs[-1] = (runs[-1][0], c)
            else:
                runs.append((c, c))
        if len(runs) > max_terms:
            return None
        out = []
        for lo_c, hi_c in runs:
            lo = np.full(len(columns), np.nan)
            hi = np.full(len(columns), np.nan)
            lo[i], hi[i] = float(lo_c), float(hi_c)
            out.append(RangeSet(lo, hi))
        return out
    return None


def extract_ranges(
    pred: ir.Expression,
    columns: Sequence[str],
    part_info: Optional[Dict[str, PartLane]] = None,
    str_lanes: Optional[frozenset] = None,
) -> Optional[RangeSet]:
    """Lower a *rewritten* skipping predicate (``min.c``/``max.c`` lanes for
    stats columns; RAW column references for partition columns, which the
    rewrite passes through) to per-column range bounds, or None when the
    shape doesn't fit (ORs, null-count tests, unknown columns → caller
    routes that query to the generic path). Strict stat comparisons are
    relaxed to non-strict — pruning may keep a boundary file it could have
    dropped, never the reverse. Partition lowerings stay exact: dictionary
    codes are discrete, so strict bounds bisect exactly."""
    col_ix = {c: i for i, c in enumerate(columns)}
    pmap = {c.lower(): c for c in (part_info or {})}
    lo = np.full(len(columns), np.nan)
    hi = np.full(len(columns), np.nan)
    empty = False
    exact = True

    def set_bounds(i: int, b_lo: float, b_hi: float) -> None:
        nonlocal empty
        if not np.isnan(b_lo):
            lo[i] = b_lo if np.isnan(lo[i]) else max(lo[i], b_lo)
        if not np.isnan(b_hi):
            hi[i] = b_hi if np.isnan(hi[i]) else min(hi[i], b_hi)

    def walk_part(e, t) -> bool:
        """Partition-column comparisons: Column(p) <op> Literal, both
        orientations (the skipping rewrite does not normalize these)."""
        nonlocal empty
        flip = {ir.Lt: ir.Gt, ir.Le: ir.Ge, ir.Gt: ir.Lt, ir.Ge: ir.Le,
                ir.Eq: ir.Eq}
        l, r = e.left, e.right
        if isinstance(l, ir.Literal) and isinstance(r, ir.Column):
            t = flip[t]
            l, r = r, l
        if not (isinstance(l, ir.Column) and isinstance(r, ir.Literal)):
            return False
        key = pmap.get(l.name.lower())
        if key is None:
            return False
        part = part_info[key]
        i = col_ix[key]
        if r.value is None:
            empty = True  # col <op> NULL matches nothing
            return True
        if t is ir.Eq:
            code = part.eq_code(r.value)
            if code is None:
                return False
            if code < 0:
                empty = True  # value absent from the table entirely
                return True
            set_bounds(i, float(code), float(code))
            return True
        op = {ir.Lt: "lt", ir.Le: "le", ir.Gt: "gt", ir.Ge: "ge"}.get(t)
        if op is None:
            return False
        b = part.bound_code(r.value, op)
        if b is None:
            return False
        set_bounds(i, *b)
        if not np.isnan(hi[i]) and hi[i] < 0:
            empty = True  # upper bound below every code
        if not np.isnan(lo[i]) and lo[i] > len(part.values) - 1:
            empty = True  # lower bound above every code
        return True

    def walk(e: ir.Expression) -> bool:
        nonlocal empty, exact
        t = type(e)
        if t is ir.And:
            return walk(e.left) and walk(e.right)
        if t is ir.Literal:
            if e.value is None or e.value is True:
                return True  # unknown/true conjunct prunes nothing
            if e.value is False:
                empty = True
                return True
            return False
        if t in (ir.Le, ir.Lt, ir.Ge, ir.Gt, ir.Eq):
            l, r = e.left, e.right
            if pmap and walk_part(e, t):
                return True
            if t is ir.Eq:
                return False  # stat lanes never see raw equality
            if not (isinstance(l, ir.Column) and isinstance(r, ir.Literal)):
                return False
            name = l.name
            base = name[4:] if name.startswith(("min.", "max.")) else None
            if (isinstance(r.value, str) and base is not None
                    and base in (str_lanes or frozenset())):
                # string stat lane: compare in 6-byte-prefix space; the
                # truncation makes the bound conservative, never exact
                from delta_tpu.ops.state_export import string_prefix_lane_value

                v = string_prefix_lane_value(r.value)
                exact = False
            elif not isinstance(r.value, (int, float)) or isinstance(r.value, bool):
                return False
            else:
                v = float(r.value)
            if name.startswith("min.") and t in (ir.Le, ir.Lt):
                i = col_ix.get(name[4:])
                if i is None:
                    return False
                if t is ir.Lt:
                    exact = False
                hi[i] = v if np.isnan(hi[i]) else min(hi[i], v)
                return True
            if name.startswith("max.") and t in (ir.Ge, ir.Gt):
                i = col_ix.get(name[4:])
                if i is None:
                    return False
                if t is ir.Gt:
                    exact = False
                lo[i] = v if np.isnan(lo[i]) else max(lo[i], v)
                return True
            return False
        return False

    if not walk(pred):
        return None
    if empty:
        return RangeSet(lo, hi, verdict="empty", exact=exact)
    if np.isnan(lo).all() and np.isnan(hi).all():
        return RangeSet(lo, hi, verdict="all", exact=exact)
    return RangeSet(lo, hi, exact=exact)


# -- the resident entry ------------------------------------------------------


@dataclass
class PlanResult:
    """One query's plan from the resident state. ``rows`` are row indices
    into the entry's layout (map to paths via ``ResidentState.paths``);
    ``overflow`` means more than K files survived and the caller must
    fall back for this query (counts stay exact)."""

    count: int
    rows: np.ndarray
    overflow: bool = False
    # 'device' | 'device-sharded' | 'host-resident' | 'verdict'
    via: str = "host-resident"


class ResidentState:
    """One table's scan-planning lanes in HBM + exact host mirrors.

    Rows are append-only (a re-added path gets a fresh row; the old one's
    alive bit drops); device arrays are padded to a power-of-two capacity so
    tail appends hit a handful of compiled kernel shapes.
    """

    def __init__(self, log_path: str, metadata_id: str, version: int,
                 columns: List[str], paths: List[str],
                 lanes: Dict[str, np.ndarray],
                 part_info: Optional[Dict[str, "PartLane"]] = None,
                 str_lanes: Optional[frozenset] = None):
        self.log_path = log_path
        self.metadata_id = metadata_id
        self.version = version
        self.columns = columns
        # partition pseudo-lanes: column name -> dictionary metadata; the
        # lane itself lives in h_lo/h_hi as min=max=code (+inf/-inf = null
        # partition value: an inverted range that no bounded query keeps)
        self.part_info: Dict[str, PartLane] = part_info or {}
        # stats columns whose lanes hold 6-byte string prefixes: literals
        # must transform through the same encoding, and bounds are never
        # exact (see state_export.string_prefix_lane_value)
        self.str_lanes: frozenset = str_lanes or frozenset()
        self.paths = list(paths)
        self.path_to_row: Dict[str, int] = {p: i for i, p in enumerate(paths)}
        n = len(paths)
        self.num_rows = n
        self.capacity = _next_pow2(max(n, 1))
        # exact host mirrors (float64 bounds; the device carries f32)
        self.h_alive = np.ones(n, bool)
        self.h_lo = lanes["min"]  # (C, n) float64
        self.h_hi = lanes["max"]
        self.h_size = lanes["size"]  # (n,) int64
        self._dead = 0
        self._dev = None  # lazily-built device arrays
        self._dev_shards = 1  # mesh shards the residency is placed over
        self._lock = threading.RLock()
        self.last_used = 0.0
        # device-memory accounting (obs/hbm_ledger: gc-backstopped)
        from delta_tpu.obs.hbm_ledger import Account

        self._hbm = Account("stateCache")

    # -- device residency -------------------------------------------------

    def _pad2(self, a: np.ndarray, fill) -> np.ndarray:
        out = np.full((a.shape[0], self.capacity), fill, np.float32)
        out[:, : a.shape[1]] = a
        return out

    def _build_device(self, shards: int = 1) -> None:
        import jax.numpy as jnp

        mins = self._pad2(_f32_down(self.h_lo), np.nan)
        maxs = self._pad2(_f32_up(self.h_hi), np.nan)
        alive = np.zeros(self.capacity, bool)
        alive[: self.num_rows] = self.h_alive[: self.num_rows]
        per_device = None
        if shards > 1:
            # sharded residency: lanes split along the file axis over the
            # 1-D state mesh, so the shard_map plan kernel reads its slice
            # locally — each device's slice accounts under ITS ledger entry
            import jax

            from delta_tpu.parallel.mesh import (NamedSharding, P,
                                                 state_mesh)
            from delta_tpu.parallel.mesh import STATE_AXIS as _AX

            mesh = state_mesh(shards)
            lane = NamedSharding(mesh, P(None, _AX))
            flat = NamedSharding(mesh, P(_AX))
            self._dev = {
                "mins": jax.device_put(mins, lane),
                "maxs": jax.device_put(maxs, lane),
                "alive": jax.device_put(alive, flat),
            }
            per = self.device_bytes // shards
            per_device = {i: per for i in range(shards)}
        else:
            self._dev = {
                "mins": jnp.asarray(mins),
                "maxs": jnp.asarray(maxs),
                "alive": jnp.asarray(alive),
            }
        self._dev_shards = shards
        self._hbm.on(self, self.device_bytes, per_device=per_device)

    @property
    def device_bytes(self) -> int:
        c = len(self.columns)
        return self.capacity * (2 * c * 4 + 1)

    def ensure_resident(self, shards: Optional[int] = None) -> None:
        with self._lock:
            if self._dev is None:
                self._build_device(shards if shards is not None else 1)

    @property
    def is_resident(self) -> bool:
        return self._dev is not None

    @property
    def resident_shards(self) -> int:
        """Mesh shards the device residency is placed over (1 = unsharded
        or not resident)."""
        return self._dev_shards if self._dev is not None else 1

    def drop_device(self) -> None:
        with self._lock:
            self._dev = None
            self._dev_shards = 1
            self._hbm.off()

    # -- incremental tail apply ------------------------------------------

    def apply_tail(self, version: int, removed_paths: Sequence[str],
                   added: Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]) -> bool:
        """Advance to ``version``: drop removed paths, append added rows
        (paths, lo(C,k), hi(C,k), size(k)). Returns False when the entry
        must be rebuilt instead (capacity overflow / too much garbage)."""
        add_paths, add_lo, add_hi, add_size = added
        k = len(add_paths)
        with self._lock:
            # Pass 1: count dead rows WITHOUT mutating the mirrors, so the
            # rebuild-needed verdict below can bail with the entry still
            # exactly at its old version (a concurrent plan_ranges holding
            # expected_version=old must keep seeing consistent state).
            dead_rows: List[int] = []
            seen_dead = set()
            for p in removed_paths:
                r = self.path_to_row.get(p)
                if r is not None and self.h_alive[r] and r not in seen_dead:
                    dead_rows.append(r)
                    seen_dead.add(r)
            for p in add_paths:
                # re-add supersedes the old row's stats
                r = self.path_to_row.get(p)
                if r is not None and self.h_alive[r] and r not in seen_dead:
                    dead_rows.append(r)
                    seen_dead.add(r)
            start = self.num_rows
            if (start + k > self.capacity
                    or self._dead + len(dead_rows) > max(1024, self.num_rows // 2)):
                return False
            # Pass 2: committed — kill exactly the rows Pass 1 counted
            # (re-added paths keep their mapping until the append below
            # overwrites it; removed paths drop theirs)
            for p in removed_paths:
                self.path_to_row.pop(p, None)
            self.h_alive[dead_rows] = False
            self._dead += len(dead_rows)
            if k:
                self.h_alive = np.concatenate([self.h_alive, np.ones(k, bool)])
                self.h_lo = np.concatenate([self.h_lo, add_lo], axis=1)
                self.h_hi = np.concatenate([self.h_hi, add_hi], axis=1)
                self.h_size = np.concatenate([self.h_size, add_size])
                for i, p in enumerate(add_paths):
                    self.paths.append(p)
                    self.path_to_row[p] = start + i
                self.num_rows = start + k
            if self._dev is not None:
                if self._dev_shards > 1:
                    # sharded lanes: drop and rebuild lazily from the
                    # updated mirrors on the next device plan — a scatter
                    # across shard-local index spaces isn't worth its
                    # compile-cache footprint, and the router already
                    # prices the cold re-upload honestly (_price_plan)
                    self._dev = None
                    self._dev_shards = 1
                    self._hbm.off()
                else:
                    self._apply_tail_device(dead_rows, start, k, add_lo, add_hi)
            self.version = version
            return True

    def map_tail_lanes(self, arr, metadata):
        """Translate a decoded tail's FileStateArrays into this entry's lane
        space: stats lanes pass through; partition codes re-map through the
        entry dictionaries, EXTENDING them for unseen values (an append that
        sorts after the current maximum keeps range lowering alive; an
        out-of-order value clears ``sorted`` — equality keeps working and
        the next rebuild re-sorts). None → caller rebuilds."""
        from delta_tpu.ops.state_export import _stat_to_lane

        with self._lock:
            if not arr.paths:  # pure-remove tail: no lanes to translate
                z = np.empty((len(self.columns), 0))
                return [], z, z.copy(), np.empty(0, np.int64)
            part_cols = sorted(self.part_info.keys())
            if part_cols != sorted(arr.partition_codes.keys()):
                return None
            stats_cols = [c for c in self.columns if c not in self.part_info]
            if stats_cols != sorted(arr.stats_min.keys()):
                return None
            mapped: Dict[str, np.ndarray] = {}
            for c in part_cols:
                part = self.part_info[c]
                tail_values = arr.partition_dicts[c]
                trans = np.empty(len(tail_values), np.int64)
                for j, v in enumerate(tail_values):
                    code = part.code_of.get(v)
                    if code is None:
                        code = len(part.values)
                        if code >= (1 << 24):
                            return None
                        if part.parsed is not None:
                            pv = _stat_to_lane(v, part.dt)
                            # a new STRING mapping to an already-present sort
                            # key ("1.0" joining "1") would split one value
                            # across two codes — rebuild (which falls back
                            # to lex order) instead of mis-serving equality
                            if pv is None or bool(np.any(part.parsed == pv)):
                                return None
                            if part.sorted and len(part.parsed):
                                part.sorted = pv > part.parsed[-1]
                            part.parsed = np.append(part.parsed, pv)
                        elif part.sorted and len(part.values):
                            part.sorted = v > part.values[-1]
                        part.values.append(v)
                        part.code_of[v] = code
                    trans[j] = code
                codes = arr.partition_codes[c]
                if len(tail_values) == 0:  # all-null tail for this column
                    mapped[c] = np.full(len(codes), -1, np.int32)
                else:
                    mapped[c] = np.where(
                        codes >= 0, trans[np.maximum(codes, 0)], -1
                    ).astype(np.int32)
            lanes = _stacked_lanes(arr, stats_cols, mapped)
            return (list(arr.paths), lanes["min"], lanes["max"],
                    lanes["size"])

    def _apply_tail_device(self, dead_rows, start, k, add_lo, add_hi) -> None:
        """One small upload + one jitted scatter/slice update in HBM.

        Shapes are bucketed (pow2 pads; out-of-range scatter indices use
        XLA drop semantics) so a steady commit stream reuses a handful of
        compiled executables."""
        import jax.numpy as jnp

        dev = self._dev
        cap = self.capacity
        d = _next_pow2(max(len(dead_rows), 1), floor=8)
        dead = np.full(d, cap, np.int32)  # cap = out of bounds -> dropped
        dead[: len(dead_rows)] = dead_rows
        a = _next_pow2(max(k, 1), floor=8)
        rows = np.full(a, cap, np.int32)
        rows[:k] = np.arange(start, start + k, dtype=np.int32)
        lo32 = np.full((self.h_lo.shape[0], a), np.nan, np.float32)
        hi32 = np.full((self.h_hi.shape[0], a), np.nan, np.float32)
        lo32[:, :k] = _f32_down(add_lo)
        hi32[:, :k] = _f32_up(add_hi)
        dev["alive"] = _scatter_bool(dev["alive"], jnp.asarray(dead), False)
        dev["alive"] = _scatter_bool(dev["alive"], jnp.asarray(rows), True)
        dev["mins"] = _scatter_cols(dev["mins"], jnp.asarray(rows), jnp.asarray(lo32))
        dev["maxs"] = _scatter_cols(dev["maxs"], jnp.asarray(rows), jnp.asarray(hi32))

    # -- serving ----------------------------------------------------------

    def plan_ranges(self, ranges: Sequence[RangeSet], k=256,
                    use_device: Optional[bool] = None,
                    expected_version: Optional[int] = None) -> Optional[List[PlanResult]]:
        """Evaluate a batch of range queries against the resident lanes:
        one dispatch, one packed-bitmap download. Structural verdicts
        short-circuit; device/host routing follows the link cost model unless
        pinned (each PlanResult records the route in ``via``).

        ``k`` caps each result's row list: a scalar for the whole batch, or
        a per-range sequence (len(ranges)) — so a multi-term (OR/IN) query
        that needs its complete row set for the post-plan union doesn't
        force every single-term query sharing the dispatch onto huge plans.

        Runs under the entry lock so a concurrent ``apply_tail`` cannot
        mutate the mirrors mid-plan; ``expected_version`` guards the other
        race — the entry advancing *past* the caller's snapshot between
        lookup and plan — by returning None (caller re-plans or falls back).
        """
        n = len(ranges)
        ks = (np.full(n, int(k), np.int64) if np.isscalar(k)
              else np.asarray(k, np.int64))
        if len(ks) != n:
            raise ValueError(f"per-range k length {len(ks)} != {n} ranges")
        priced = None
        with self._lock:
            if expected_version is not None and self.version != expected_version:
                return None
            real_ix = [i for i, r in enumerate(ranges) if r.verdict is None]
            out: List[Optional[PlanResult]] = [None] * n
            alive_rows = np.nonzero(self.h_alive[: self.num_rows])[0]
            for i, r in enumerate(ranges):
                if r.verdict == "empty":
                    out[i] = PlanResult(0, np.empty(0, np.int64), via="verdict")
                elif r.verdict == "all":
                    out[i] = PlanResult(len(alive_rows), alive_rows[:ks[i]],
                                        overflow=len(alive_rows) > ks[i],
                                        via="verdict")
            if not real_ix:
                return out  # type: ignore[return-value]
            lo = np.stack([ranges[i].lo for i in real_ix])  # (M, C)
            hi = np.stack([ranges[i].hi for i in real_ix])
            real_ks = ks[real_ix]
            if use_device is None:
                use_device, priced = self._route_plan(len(real_ix))
            import time as _time

            shards = self._plan_shards(priced, len(real_ix)) if use_device else 1
            t0 = _time.perf_counter_ns()
            results = (self._plan_device(lo, hi, real_ks, shards=shards)
                       if use_device
                       else self._plan_host(lo, hi, real_ks))
            plan_s = (_time.perf_counter_ns() - t0) / 1e9
            ran_shards = self._dev_shards if use_device else 1
            via = ("device-sharded" if ran_shards > 1
                   else "device" if use_device else "host-resident")
            for j, i in enumerate(real_ix):
                results[j].via = via
                out[i] = results[j]
        # router audit OUTSIDE the entry lock: the ledger (and, with
        # calibration enabled, its state-file read-modify-write) must not
        # serialize concurrent planners or a tail apply. Only AUTO-routed
        # batches audit — a pinned mode made no priceable decision (and the
        # disabled/forced paths never pay the link probe just to price one).
        if priced is not None:
            from delta_tpu.obs import router_audit

            device_s, host_s, cells, device_fixed_s, sharded_s, _ns = priced
            # per-cell calibrator sample with the predictor's FIXED terms
            # (dispatch latency, bitmap download, cold upload) subtracted
            # first — the prediction re-adds them, so a sample that folded
            # them in would double-count the overhead and overpredict the
            # device forever
            if use_device:
                eff = plan_s - device_fixed_s
                # a sharded run did cells/shards per-device work: sample the
                # per-cell rate at the per-shard cell count so calibration
                # fits the device, not the mesh
                cal_cells = cells // max(ran_shards, 1)
                samples = ([("DEVICE_PRUNE_S_PER_CELL", cal_cells, eff)]
                           if eff > 0 else [])
            else:
                samples = [("HOST_PRUNE_S_PER_CELL", cells, plan_s)]
            predictions = {"device": device_s, "host-resident": host_s}
            if sharded_s is not None:
                predictions["device-sharded"] = sharded_s
            router_audit.record_audit(
                "scan.plan", self.log_path, via,
                predictions, plan_s,
                units={"cells": cells, "queries": len(real_ix)},
                samples=samples, log_path=self.log_path,
                # once per planned query: the calibrator state-file write
                # must be interval-throttled, not per-plan
                calibration_flush=False,
            )
        return out  # type: ignore[return-value]

    def _price_plan(self, m: int) -> Tuple[float, float, int, float,
                                           Optional[float], int]:
        """The router's cost model for planning ``m`` range queries against
        this entry: (device_s, host_s, cells, device_fixed_s, sharded_s,
        shards). ``device_fixed_s`` is the cell-count-independent part of
        the device price (dispatch + download + cold upload) — what the
        calibrator must subtract from a measured sample before fitting the
        per-cell rate. ``sharded_s`` prices the same plan over the
        shard_map mesh (None when no multi-device mesh is feasible) with
        the calibratable per-shard constants, so the audit record carries
        the sharded-vs-single decision. Constants read through
        ``link.constant`` so calibration feeds back."""
        from delta_tpu.parallel import link

        cells = m * self.num_rows * max(len(self.columns), 1)
        host_s = cells * link.constant("HOST_PRUNE_S_PER_CELL")
        p = link.profile()
        down_bytes = m * max(self.capacity // BLOCK // 8, 1)
        fixed_s = 2 * p.latency_s + p.download_s(down_bytes)
        if self._dev is None:
            # cold build ships the full lanes once; amortized over later
            # queries, but charge it to this call for honest routing
            fixed_s += p.upload_s(self.device_bytes)
        device_s = fixed_s + cells * link.constant("DEVICE_PRUNE_S_PER_CELL")
        shards = self._feasible_shards()
        sharded_s = None
        if shards > 1:
            sharded_s = fixed_s + link.sharded_plan_device_s(cells, shards, p)
        return device_s, host_s, cells, fixed_s, sharded_s, shards

    def _feasible_shards(self) -> int:
        """Largest pow2 shard count the mesh and the lane layout admit: the
        capacity must split into whole 1024-file BLOCKs per shard (capacity
        is pow2, so divisibility is monotone in the shard count). 1 when
        sharded planning is disabled or there is one device."""
        if not conf.get_bool("delta.tpu.distributed.plan.enabled", True):
            return 1
        if conf.get("delta.tpu.distributed.plan.mode", "auto") == "off":
            return 1
        try:
            import jax

            nd = len(jax.devices())
        except Exception:
            return 1
        s = 1
        while s * 2 <= nd and self.capacity % (s * 2 * BLOCK) == 0:
            s *= 2
        return s

    def _plan_shards(self, priced, m: int) -> int:
        """Shard count for a device-routed plan batch. Existing residency
        wins (no placement thrash); otherwise "force" takes the full mesh
        and "auto" takes it only when the per-shard cost model says the
        dispatch+gather tax beats the 1/shards cell scan win."""
        if self._dev is not None:
            return self._dev_shards
        s = self._feasible_shards()
        if s <= 1:
            return 1
        if conf.get("delta.tpu.distributed.plan.mode", "auto") == "force":
            return s
        from delta_tpu.parallel import link

        if priced is not None:
            device_s, _h, _c, fixed_s, sharded_s, shards = priced
            return shards if (sharded_s is not None
                              and sharded_s < device_s) else 1
        # pinned device route (devicePlan.mode=force) skipped batch pricing:
        # price only the sharded-vs-single choice here
        cells = m * self.num_rows * max(len(self.columns), 1)
        p = link.profile()
        single = cells * link.constant("DEVICE_PRUNE_S_PER_CELL")
        return s if link.sharded_plan_device_s(cells, s, p) < single else 1

    def _route_plan(self, m: int):
        """(use_device, priced) for ``m`` range queries: the enabled/mode
        short-circuits run BEFORE any pricing, so a disabled or pinned
        deployment never pays the link probe — and gets no audit record,
        since no priceable decision was made. ``priced`` is the
        ``_price_plan`` tuple in auto mode, else None. The device side
        enters at its best price (sharded when the mesh wins)."""
        if not conf.get_bool("delta.tpu.stateCache.devicePlan.enabled", True):
            return False, None
        mode = conf.get("delta.tpu.stateCache.devicePlan.mode", "auto")
        if mode == "force":
            return True, None
        if mode == "off":
            return False, None
        priced = self._price_plan(m)
        best_device = (priced[0] if priced[4] is None
                       else min(priced[0], priced[4]))
        return best_device < priced[1], priced

    def _plan_host(self, lo: np.ndarray, hi: np.ndarray,
                   ks: np.ndarray) -> List[PlanResult]:
        n = self.num_rows
        mins, maxs = self.h_lo[:, :n], self.h_hi[:, :n]
        alive = self.h_alive[:n]
        out = []
        for q in range(lo.shape[0]):
            keep = alive.copy()
            for c in range(lo.shape[1]):
                if not np.isnan(lo[q, c]):
                    keep &= ~(maxs[c] < lo[q, c])  # NaN stat keeps
                if not np.isnan(hi[q, c]):
                    keep &= ~(mins[c] > hi[q, c])
            rows = np.nonzero(keep)[0]
            k = ks[q]
            out.append(PlanResult(len(rows), rows[:k], overflow=len(rows) > k))
        return out

    def _plan_device(self, lo: np.ndarray, hi: np.ndarray,
                     ks: np.ndarray, shards: int = 1) -> List[PlanResult]:
        """Coarse-fine plan: the device culls 1024-file BLOCKS (one dispatch
        over the resident f32 lanes, one tiny packed-bitmap download); the
        host then evaluates exactly (float64 mirrors) inside the surviving
        blocks only. Index extraction never runs on device — measured on a
        v5e, a vmapped ``nonzero``/``top_k`` over (256, 1M) costs 0.7-2.4 s
        where the block-bitmap reduction costs ~0.1 s — and the fine pass
        erases the f32 slop, so device results equal host results exactly.

        With sharded residency (``shards > 1``) the cull runs as a
        shard_map over the state mesh: each device evaluates its 1/shards
        slice of the lanes, the block bitmaps all-gather along the file
        axis, and the identical host fine pass finishes — so sharded
        results equal single-device results equal host results exactly,
        by construction."""
        import jax.numpy as jnp

        self.ensure_resident(shards)
        m = lo.shape[0]
        mb = _next_pow2(m, floor=8)  # bucket the query-batch dim too
        lo_p = np.full((mb, lo.shape[1]), np.nan, np.float32)
        hi_p = np.full((mb, hi.shape[1]), np.nan, np.float32)
        lo_p[:m] = _f32_down(lo)
        hi_p[:m] = _f32_up(hi)
        try:
            if self._dev_shards > 1:
                from delta_tpu.utils import telemetry

                telemetry.bump_counter("dist.plan.sharded")
                bl = _sharded_block_kernel(
                    self._dev["mins"], self._dev["maxs"], self._dev["alive"],
                    jnp.asarray(lo_p), jnp.asarray(hi_p), BLOCK,
                    self._dev_shards,
                )
                blocks = np.asarray(bl)[:m].astype(bool)
            else:
                bits = _block_kernel(
                    self._dev["mins"], self._dev["maxs"], self._dev["alive"],
                    jnp.asarray(lo_p), jnp.asarray(hi_p), BLOCK,
                )
                n_blocks = self.capacity // BLOCK
                blocks = np.unpackbits(np.asarray(bits)[:m], axis=1,
                                       count=n_blocks)
        except Exception:  # noqa: BLE001 — degradation ladder, first rung:
            # a shard_map/lowering failure (mesh reshape race, OOM on the
            # coarse cull) must cost latency, not the query — the host fine
            # pass over every block is the same exact evaluation the device
            # pass would have narrowed
            from delta_tpu.utils import telemetry

            telemetry.bump_counter("dist.degraded.plan")
            return self._plan_host(lo, hi, ks)
        return self._fine_pass(blocks, lo, hi, ks)

    def _fine_pass(self, blocks: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                   ks: np.ndarray) -> List[PlanResult]:
        """Exact float64 host evaluation inside the device-surviving blocks
        — shared by the single-device and sharded coarse passes."""
        n = self.num_rows
        mins, maxs, alive = self.h_lo[:, :n], self.h_hi[:, :n], self.h_alive[:n]
        out = []
        for q in range(lo.shape[0]):
            hit = np.nonzero(blocks[q])[0]
            if not len(hit):
                out.append(PlanResult(0, np.empty(0, np.int64)))
                continue
            cand = np.concatenate([
                np.arange(b * BLOCK, min((b + 1) * BLOCK, n)) for b in hit
            ])
            cand = cand[cand < n]
            keep = alive[cand].copy()
            for c in range(lo.shape[1]):
                if not np.isnan(lo[q, c]):
                    keep &= ~(maxs[c][cand] < lo[q, c])
                if not np.isnan(hi[q, c]):
                    keep &= ~(mins[c][cand] > hi[q, c])
            rows = cand[keep]
            k = ks[q]
            out.append(PlanResult(len(rows), rows[:k], overflow=len(rows) > k))
        return out


@functools.lru_cache(maxsize=None)
def _scatter_bool_fn(value: bool):
    import jax

    return jax.jit(lambda a, r: a.at[r].set(value, mode="drop"))


def _scatter_bool(arr, rows, value: bool):
    return _scatter_bool_fn(value)(arr, rows)


@functools.lru_cache(maxsize=None)
def _scatter_cols_fn():
    import jax

    return jax.jit(lambda a, r, v: a.at[:, r].set(v, mode="drop"))


def _scatter_cols(arr, rows, vals):
    return _scatter_cols_fn()(arr, rows, vals)


# device block-cull granularity: pow2 ≤ the capacity floor in _next_pow2, so
# the padded capacity always divides evenly
BLOCK = 1024


@functools.lru_cache(maxsize=None)
def _block_kernel_fn(block: int):
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    def kernel(mins, maxs, alive, lo, hi):
        # mins/maxs: (C, cap) f32; alive: (cap,) bool; lo/hi: (M, C) f32.
        # keep[m, f] = alive[f] AND over columns: the file's [min,max] range
        # can intersect the query's [lo,hi]; NaN (either side) = no bound.
        keep = jnp.broadcast_to(alive[None, :], (lo.shape[0], alive.shape[0]))
        for c in range(lo.shape[1]):  # static unroll: C is a lane count
            mn, mx = mins[c][None, :], maxs[c][None, :]
            lo_c, hi_c = lo[:, c:c + 1], hi[:, c:c + 1]
            keep = keep & (jnp.isnan(mx) | jnp.isnan(lo_c) | (mx >= lo_c))
            keep = keep & (jnp.isnan(mn) | jnp.isnan(hi_c) | (mn <= hi_c))
        blocks = keep.reshape(keep.shape[0], keep.shape[1] // block, block).any(axis=2)
        return jnp.packbits(blocks, axis=1)

    return jax.jit(kernel)


def _block_kernel(mins, maxs, alive, lo, hi, block: int):
    return _block_kernel_fn(block)(mins, maxs, alive, lo, hi)


@functools.lru_cache(maxsize=None)
def _sharded_block_kernel_fn(block: int, ncols: int, shards: int):
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    from delta_tpu.parallel.mesh import P, STATE_AXIS, state_mesh
    from delta_tpu.utils.jaxcompat import shard_map

    mesh = state_mesh(shards)

    def kernel(mins, maxs, alive, lo, hi):
        # per-shard slices: mins/maxs (C, cap/shards), alive (cap/shards,);
        # lo/hi replicated (M, C). Same can-intersect test as _block_kernel
        # over this shard's files; each shard reduces its own 1024-file
        # blocks and the out-spec all-gathers the block maps along the
        # file axis — so the merged map is bit-identical to the
        # single-device cull.
        keep = jnp.broadcast_to(alive[None, :], (lo.shape[0], alive.shape[0]))
        for c in range(ncols):  # static unroll: C is a lane count
            mn, mx = mins[c][None, :], maxs[c][None, :]
            lo_c, hi_c = lo[:, c:c + 1], hi[:, c:c + 1]
            keep = keep & (jnp.isnan(mx) | jnp.isnan(lo_c) | (mx >= lo_c))
            keep = keep & (jnp.isnan(mn) | jnp.isnan(hi_c) | (mn <= hi_c))
        blocks = keep.reshape(
            keep.shape[0], keep.shape[1] // block, block
        ).any(axis=2)
        return blocks.astype(jnp.uint8)

    sm = shard_map(
        kernel, mesh=mesh,
        in_specs=(P(None, STATE_AXIS), P(None, STATE_AXIS), P(STATE_AXIS),
                  P(), P()),
        out_specs=P(None, STATE_AXIS),
    )
    return jax.jit(sm)


def _sharded_block_kernel(mins, maxs, alive, lo, hi, block: int, shards: int):
    return _sharded_block_kernel_fn(block, lo.shape[1], shards)(
        mins, maxs, alive, lo, hi
    )


# -- building entries from snapshots ----------------------------------------


def _lanes_from_arrays(arr, columns: Sequence[str]):
    lo = np.stack([arr.stats_min[c] for c in columns]) if columns else np.empty((0, arr.num_files))
    hi = np.stack([arr.stats_max[c] for c in columns]) if columns else np.empty((0, arr.num_files))
    return {"min": lo, "max": hi, "size": arr.size.astype(np.int64)}


def _string_stat_cols(metadata) -> List[str]:
    from delta_tpu.schema.types import StringType

    pset = set(metadata.partition_columns)
    return sorted(f.name for f in metadata.schema.fields
                  if isinstance(f.data_type, StringType) and f.name not in pset)


def _build_part_info(arr, metadata):
    """Value-sort each partition dictionary (typed order when the column
    type parses every value, else code-point order), remap codes to ranks,
    and emit (part_info, remapped_codes) — or None when a dictionary is too
    large for exact f32 lanes."""
    from delta_tpu.ops.state_export import _NUMERIC, _stat_to_lane

    types = {f.name: f.data_type for f in metadata.schema.fields}
    part_info: Dict[str, PartLane] = {}
    remapped: Dict[str, np.ndarray] = {}
    for c in sorted(arr.partition_codes.keys()):
        values = list(arr.partition_dicts[c])
        if len(values) > (1 << 24):  # codes must stay f32-exact
            return None
        dt = types.get(c)
        parsed = None
        if isinstance(dt, _NUMERIC):
            p = [_stat_to_lane(v, dt) for v in values]
            if all(x is not None for x in p):
                cand = np.asarray(p, np.float64)
                # duplicate sort keys ("1" vs "1.0") would make a value
                # range span two codes non-contiguously — fall back to lex
                if len(np.unique(cand)) == len(cand):
                    parsed = cand
        if parsed is not None:
            order = np.argsort(parsed, kind="stable")
            parsed = parsed[order]
        else:
            order = np.argsort(np.asarray(values, object), kind="stable")
            dt = None
        rank = np.empty(len(values), np.int64)
        rank[order] = np.arange(len(values))
        codes = arr.partition_codes[c]
        if len(values) == 0:
            # every alive file carries null for this column: no dictionary,
            # all codes -1 (the inverted-range lane prunes them exactly)
            remapped[c] = np.full(len(codes), -1, np.int32)
        else:
            remapped[c] = np.where(
                codes >= 0, rank[np.maximum(codes, 0)], -1).astype(np.int32)
        svals = [values[i] for i in order]
        part_info[c] = PartLane(
            values=svals, parsed=parsed,
            code_of={v: i for i, v in enumerate(svals)}, dt=dt,
        )
    return part_info, remapped


def _stacked_lanes(arr, stats_cols, part_codes: Dict[str, np.ndarray]):
    """Combined lane stack: stats columns first (sorted), then partition
    pseudo-lanes (sorted) — matching the entry's ``columns`` order."""
    lanes = _lanes_from_arrays(arr, stats_cols)
    if part_codes:
        lo_rows, hi_rows = [], []
        for c in sorted(part_codes.keys()):
            lo_r, hi_r = _part_lane_rows(part_codes[c])
            lo_rows.append(lo_r)
            hi_rows.append(hi_r)
        lanes["min"] = np.concatenate([lanes["min"], np.stack(lo_rows)], axis=0)
        lanes["max"] = np.concatenate([lanes["max"], np.stack(hi_rows)], axis=0)
    return lanes


def build_entry(snapshot) -> Optional[ResidentState]:
    """Full build of a resident entry from a snapshot's columnar state —
    partitioned tables included (dictionary-coded partition lanes; the
    reference's primary pruning path, `PartitionFiltering.scala:27-43`,
    served from the same block-cull kernel). None when the shape is
    unsupported (odd stats / oversized dictionaries)."""
    from delta_tpu.ops.state_export import arrays_from_columns

    str_cols = _string_stat_cols(snapshot.metadata)
    arr = arrays_from_columns(
        snapshot._columnar, snapshot._alive_mask, snapshot.metadata,
        string_prefix_cols=str_cols,
    )
    if arr is None:
        return None
    built = _build_part_info(arr, snapshot.metadata)
    if built is None:
        return None
    part_info, remapped = built
    stats_cols = sorted(arr.stats_min.keys())
    columns = stats_cols + sorted(part_info.keys())
    return ResidentState(
        log_path=snapshot.delta_log.log_path,
        metadata_id=snapshot.metadata.id,
        version=snapshot.version,
        columns=columns,
        paths=list(arr.paths),
        lanes=_stacked_lanes(arr, stats_cols, remapped),
        part_info=part_info,
        str_lanes=frozenset(str_cols),
    )


def _decode_tail(snapshot, from_version: int):
    """Decode commits (from_version, snapshot.version] to (removed_paths,
    FileStateArrays) or None when incremental apply isn't safe (metadata
    change in the tail, missing commit files, undecodable shapes). The
    caller maps the arrays into its entry's lane space (partition code
    translation happens there, under the entry lock)."""
    from delta_tpu.log.columnar import decode_segment
    from delta_tpu.ops.state_export import arrays_from_columns
    from delta_tpu.protocol import filenames
    from delta_tpu.protocol.actions import Metadata

    log = snapshot.delta_log
    paths = [
        f"{log.log_path}/{filenames.delta_file(v)}"
        for v in range(from_version + 1, snapshot.version + 1)
    ]
    try:
        cols = decode_segment(log.store, [], paths)
    except Exception:
        return None
    if any(isinstance(a, Metadata) for a in cols.other_actions):
        return None  # schema/config may have changed -> rebuild
    w = cols.winner_mask()
    alive, _ = cols.replay(winner=w)
    dead_winner = w & ~alive
    removed = cols.paths_for(np.nonzero(dead_winner)[0])
    arr = arrays_from_columns(
        cols, alive, snapshot.metadata,
        string_prefix_cols=_string_stat_cols(snapshot.metadata))
    if arr is None:
        return None
    return removed, arr


class DeviceStateCache:
    """Process-wide registry of :class:`ResidentState` entries with an HBM
    byte budget (`delta.tpu.stateCache.maxBytes`) and LRU eviction — the
    TPU analogue of the reference's `StateCache` Spark-memory cache."""

    _instance: Optional["DeviceStateCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._entries: Dict[str, ResidentState] = {}
        self._lock = threading.RLock()
        self._build_locks: Dict[str, threading.Lock] = {}
        self._tick = 0

    @classmethod
    def instance(cls) -> "DeviceStateCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = DeviceStateCache()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            cls._instance = None

    def invalidate(self, log_path: str) -> None:
        with self._lock:
            e = self._entries.pop(log_path, None)
            self._build_locks.pop(log_path, None)
            if e is not None:
                e.drop_device()  # return its bytes to the HBM ledger

    def _lookup(self, key: str, snapshot):
        """Registry-lock lookup. Returns (entry_or_None, verdict): 'hit',
        'older' (serve from host), or 'advance' (tail apply / rebuild)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.metadata_id != snapshot.metadata.id:
                e = None  # table replaced in place
            if e is None:
                return None, "advance"
            if e.version > snapshot.version:
                return None, "older"  # time travel below residency
            return e, ("hit" if e.version == snapshot.version else "advance")

    def get(self, snapshot) -> Optional[ResidentState]:
        """Entry current at the snapshot's version: cache hit, incremental
        tail apply, or full rebuild. None when unsupported or disabled.

        The registry lock covers only lookups/inserts; the seconds-long
        decode/build work runs under a per-table build lock so a cold build
        for one table never stalls cache hits for another."""
        if not conf.get_bool("delta.tpu.stateCache.enabled", True):
            return None
        key = snapshot.delta_log.log_path
        with self._lock:
            self._tick += 1
            tick = self._tick
            build_lock = self._build_locks.setdefault(key, threading.Lock())
        e, verdict = self._lookup(key, snapshot)
        if verdict == "older":
            return None
        if verdict == "hit":
            e.last_used = tick
            return e
        with build_lock:
            # re-check: another thread may have advanced/built meanwhile
            e, verdict = self._lookup(key, snapshot)
            if verdict == "older":
                return None
            if verdict == "hit":
                e.last_used = tick
                return e
            from delta_tpu.utils import telemetry

            if e is not None:  # behind: try the incremental tail
                with telemetry.record_operation(
                    "delta.stateCache.tailApply",
                    {"fromVersion": e.version, "toVersion": snapshot.version},
                    path=snapshot.delta_log.data_path,
                ) as tev:
                    tail = _decode_tail(snapshot, e.version)
                    ok = False
                    if tail is not None:
                        removed, arr = tail
                        added = e.map_tail_lanes(arr, snapshot.metadata)
                        if added is not None:
                            ok = e.apply_tail(snapshot.version, removed, added)
                    tev.data["applied"] = ok
                if not ok:
                    e = None
            if e is None:
                with telemetry.record_operation(
                    "delta.stateCache.build",
                    {"version": snapshot.version},
                    path=snapshot.delta_log.data_path,
                ) as bev:
                    e = build_entry(snapshot)
                    bev.data["built"] = e is not None
                telemetry.bump_counter("stateCache.builds")
                if e is None:
                    return None
                with self._lock:
                    old = self._entries.get(key)
                    if old is not None and old is not e:
                        old.drop_device()  # rebuilt: old entry's HBM returns
                    self._entries[key] = e
            e.last_used = tick
            with self._lock:
                self._evict_over_budget(keep=key)
            # state-cache growth can push the PROCESS-WIDE device budget
            # over: apply key-cache LRU pressure now (no entry/registry
            # lock held here), not at the next merge
            from delta_tpu.obs import hbm_ledger

            hbm_ledger.maybe_relieve()
            return e

    def _evict_over_budget(self, keep: str) -> None:
        # HBM budget: drop device arrays LRU (host mirrors keep serving)
        budget = int(conf.get("delta.tpu.stateCache.maxBytes", 2 << 30))
        resident = [(p, e) for p, e in self._entries.items() if e.is_resident]
        total = sum(e.device_bytes for _, e in resident)
        for p, e in sorted(resident, key=lambda kv: kv[1].last_used):
            if total <= budget:
                break
            if p == keep:
                continue
            e.drop_device()
            total -= e.device_bytes
        # host budget: entries (mirrors + path dictionaries) are themselves
        # sizable — drop whole tables LRU beyond maxEntries
        max_entries = int(conf.get("delta.tpu.stateCache.maxEntries", 16))
        if len(self._entries) > max_entries:
            for p, e in sorted(self._entries.items(),
                               key=lambda kv: kv[1].last_used):
                if p == keep:
                    continue
                self._entries.pop(p, None)
                self._build_locks.pop(p, None)
                e.drop_device()  # return its bytes to the HBM ledger
                if len(self._entries) <= max_entries:
                    break
